"""graftlint Level 2 (source/AST) + CLI gate.

Adversarial source fixtures for GL101/GL102/GL103, inline suppression,
and — the CI gate — ``tools/graftlint.py`` over the whole
``incubator_mxnet_tpu/`` package must exit 0: idiom violations fail
tier-1 from now on."""
import os
import sys
import textwrap

import pytest

from incubator_mxnet_tpu.analysis import Severity, lint_source
from incubator_mxnet_tpu.analysis.source_lint import lint_paths

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, path="pkg/mod.py"):
    return lint_source(textwrap.dedent(src), path=path)


# ---------------------------------------------------------------------------
# GL101 — shard_map import origin
# ---------------------------------------------------------------------------

def test_gl101_shard_map_from_jax_experimental():
    diags = _lint("""
        from jax.experimental.shard_map import shard_map
    """)
    assert [d.code for d in diags] == ["GL101"]
    assert "parallel.mesh" in diags[0].message


def test_gl101_shard_map_from_jax_toplevel():
    diags = _lint("""
        from jax import shard_map
    """)
    assert [d.code for d in diags] == ["GL101"]


def test_gl101_compat_home_exempt():
    src = """
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
    """
    assert not _lint(src, path="incubator_mxnet_tpu/parallel/mesh.py")
    assert len(_lint(src, path="somewhere/else.py")) == 2


def test_gl101_importing_the_compat_home_is_clean():
    assert not _lint("""
        from incubator_mxnet_tpu.parallel.mesh import shard_map
        from .mesh import shard_map
    """)


# ---------------------------------------------------------------------------
# GL102 — side effects inside jit
# ---------------------------------------------------------------------------

def test_gl102_time_and_np_random_in_jit():
    diags = _lint("""
        import time
        import numpy as np
        import jax

        @jax.jit
        def step(x):
            t0 = time.time()
            noise = np.random.rand(4)
            return x + noise, t0
    """)
    assert sorted(d.code for d in diags) == ["GL102", "GL102"]
    assert all(d.severity == Severity.ERROR for d in diags)
    assert any("baked into" in d.message for d in diags)


def test_gl102_stdlib_random_but_not_jax_random():
    diags = _lint("""
        import random
        from functools import partial
        import jax

        @partial(jax.jit, static_argnums=0)
        def bad(n, x):
            return x * random.random()
    """)
    assert [d.code for d in diags] == ["GL102"]
    # `from jax import random` is NOT the stdlib PRNG — no finding
    assert not _lint("""
        import jax
        from jax import random

        @jax.jit
        def ok(key, x):
            return x + random.normal(key, x.shape)
    """)


def test_gl102_other_jits_not_flagged():
    """numba-style JITs allow host side effects — resolved through the
    import map, they must not match."""
    assert not _lint("""
        import time
        import numpy as np
        import numba
        from numba import jit

        @numba.jit
        def a(x):
            return np.random.rand(4) + time.time()

        @jit
        def b(x):
            return np.random.rand(4)
    """)


def test_gl102_only_inside_jit_decorated():
    assert not _lint("""
        import time
        import numpy as np

        def eager_benchmark(x):
            t0 = time.time()
            return np.random.rand(4), t0
    """)


# ---------------------------------------------------------------------------
# GL103 — PartitionSpec hygiene
# ---------------------------------------------------------------------------

def test_gl103_fstring_and_int_specs():
    diags = _lint("""
        from jax.sharding import PartitionSpec as P

        def make(ax):
            bad1 = P(f"{ax}")
            bad2 = P(0, None)
            ok = P("dp", None)
            return bad1, bad2, ok
    """)
    assert sorted(d.code for d in diags) == ["GL103", "GL103"]
    assert any("f-string" in d.message for d in diags)
    assert any("integer" in d.message for d in diags)


def test_gl103_attribute_path_partition_spec():
    """PartitionSpec reached through an attribute chain is checked too."""
    diags = _lint("""
        import jax

        def make(ax):
            return jax.sharding.PartitionSpec(f"{ax}")
    """)
    assert [d.code for d in diags] == ["GL103"]


def test_gl103_requires_spec_import_evidence():
    """An unrelated local function named P is not a PartitionSpec."""
    assert not _lint("""
        def P(x):
            return x

        y = P(f"hello")
    """)


# ---------------------------------------------------------------------------
# GL008 — checkpoint from a data loop without iterator state
# ---------------------------------------------------------------------------

def test_gl008_save_in_stateful_loop_without_data_iter():
    from incubator_mxnet_tpu.analysis import (
        CODES, check_checkpoint_without_iter_state)

    # cataloged (append-only contract, docs/ANALYSIS.md)
    assert CODES["GL008"][0] == Severity.WARNING
    src = """
        def train(step, train_iter, d):
            for batch in train_iter:
                step(batch.data[0], batch.label[0])
                step.save_checkpoint(d)
    """
    diags = _lint(src)
    assert [d.code for d in diags] == ["GL008"]
    assert diags[0].severity == Severity.WARNING
    assert "replays the epoch" in diags[0].message
    assert "data_iter" in diags[0].hint
    # the named core is directly callable on source text
    import textwrap

    direct = check_checkpoint_without_iter_state(textwrap.dedent(src))
    assert [d.code for d in direct] == ["GL008"]
    # attach_checkpoint inside the loop is the same hazard
    assert [d.code for d in _lint("""
        def train(step, loader, d):
            for i, batch in enumerate(loader):
                step.attach_checkpoint(d, every=100)
    """)] == ["GL008"]


def test_gl008_nested_stateful_loops_one_diagnostic_per_call():
    # ast.walk reaches the same call from BOTH enclosing stateful
    # loops — still exactly one diagnostic per save site
    diags = _lint("""
        def train(step, loader, loader2, d):
            for a in loader:
                for b in loader2:
                    step.save_checkpoint(d)
    """)
    assert [d.code for d in diags] == ["GL008"]


def test_gl008_clean_patterns():
    # data_iter= passed -> clean
    assert not _lint("""
        def train(step, train_iter, d):
            for batch in train_iter:
                step.save_checkpoint(d, data_iter=train_iter)
    """)
    # position-free iterables (literals, range) -> clean; call outside
    # any loop -> clean
    assert not _lint("""
        def train(step, d, batches):
            for batch in [1, 2, 3]:
                step.save_checkpoint(d)
            for i in range(10):
                step.attach_checkpoint(d)
            step.save_checkpoint(d)
    """)
    # inline suppression works for GL008 too
    assert not _lint("""
        def train(step, loader, d):
            for batch in loader:
                step.save_checkpoint(d)  # graftlint: disable=GL008
    """)


# ---------------------------------------------------------------------------
# GL009 — process-local checkpoint dir in a jax.distributed world
# ---------------------------------------------------------------------------

def test_gl009_process_local_ckpt_dir():
    import tempfile

    from incubator_mxnet_tpu.analysis import (
        CODES, check_process_local_ckpt_dir)

    assert CODES["GL009"][0] == Severity.WARNING
    tmp = tempfile.gettempdir()
    diags = check_process_local_ckpt_dir(os.path.join(tmp, "ckpts"), 4)
    assert [d.code for d in diags] == ["GL009"]
    assert diags[0].severity == Severity.WARNING
    assert "4 processes" in diags[0].message
    assert "shared filesystem" in diags[0].hint
    # relative paths resolve per-process working dirs: flagged too
    assert [d.code for d in check_process_local_ckpt_dir("ckpts", 2)] \
        == ["GL009"]
    # a shared absolute path is clean; so is any dir at world size 1
    assert check_process_local_ckpt_dir("/shared/nfs/ckpts", 4) == []
    assert check_process_local_ckpt_dir(os.path.join(tmp, "c"), 1) == []


def test_gl009_fires_at_manager_construction(tmp_path):
    """The one wired emission point: constructing a CheckpointManager
    with process_count > 1 over a process-local directory warns with
    the GL009 diagnostic; a single-process manager never does."""
    import warnings as _w

    from incubator_mxnet_tpu.parallel import CheckpointManager

    with pytest.warns(UserWarning, match="GL009"):
        CheckpointManager(str(tmp_path / "c"), process_index=0,
                          process_count=2)
    with _w.catch_warnings():
        _w.simplefilter("error")
        CheckpointManager(str(tmp_path / "c"), process_count=1)


def test_inline_suppression():
    diags = _lint("""
        from jax import shard_map  # graftlint: disable=GL101
    """)
    assert not diags
    diags = _lint("""
        from jax import shard_map  # graftlint: disable
    """)
    assert not diags
    diags = _lint("""
        from jax import shard_map  # graftlint: disable=GL102
    """)
    assert [d.code for d in diags] == ["GL101"]


# ---------------------------------------------------------------------------
# the CI gate
# ---------------------------------------------------------------------------

def test_repo_package_is_idiom_clean():
    """Level 2 over the real package: zero findings of any severity.
    New code that imports shard_map from jax, calls time/np.random
    inside jit, or builds f-string specs fails tier-1 here."""
    report = lint_paths([os.path.join(ROOT, "incubator_mxnet_tpu")])
    assert not report.errors, "\n" + report.format()
    assert not report.warnings, "\n" + report.format()


def test_cli_exit_codes(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import graftlint
    finally:
        sys.path.pop(0)
    # clean package -> 0
    assert graftlint.main([os.path.join(ROOT, "incubator_mxnet_tpu",
                                        "analysis")]) == 0
    # a violating file -> 1
    bad = tmp_path / "bad.py"
    bad.write_text("from jax.experimental.shard_map import shard_map\n")
    assert graftlint.main([str(tmp_path)]) == 1
    # suppressed -> 0
    assert graftlint.main([str(tmp_path), "--suppress", "GL101"]) == 0


def test_cli_select_ignore_filters(tmp_path):
    """--select/--ignore code filters: CI can gate on a precise code set
    while other codes stay advisory; ignored codes drop from the exit
    status too."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import graftlint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import shard_map\n"
                   "from jax.sharding import PartitionSpec as P\n"
                   "s = P(0)\n")  # GL101 + GL103
    # unfiltered: both errors gate
    assert graftlint.main([str(tmp_path)]) == 1
    # select only GL103 -> still 1 (GL103 is an error); GL101 dropped
    assert graftlint.main([str(tmp_path), "--select", "GL103"]) == 1
    # ignore both -> clean exit
    assert graftlint.main([str(tmp_path), "--ignore", "GL101,GL103"]) == 0
    # select a code the file does not violate -> clean exit
    assert graftlint.main([str(tmp_path), "--select", "GL102"]) == 0
    # --suppress stays an alias of --ignore
    assert graftlint.main([str(tmp_path), "--suppress", "GL101",
                           "--ignore", "GL103"]) == 0


def test_cli_gate_over_package_with_select():
    """Tier-1 wiring: the CLI gates the real package on the GL10x error
    codes (the invocation CI runs)."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import graftlint
    finally:
        sys.path.pop(0)
    assert graftlint.main([os.path.join(ROOT, "incubator_mxnet_tpu"),
                           "--select", "GL101,GL102,GL103"]) == 0


def test_gl007_legacy_save_states_from_zero1_fused_trainer():
    """GL007 gate: a zero=1 fused step built from a Trainer warns that
    the legacy save_states path is still reachable (it cannot round-trip
    dp-sharded optimizer state), and the Trainer raises if it IS called
    — pointing at the shard-aware checkpoint API."""
    import warnings

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.analysis import (CODES, Severity as Sev,
                                              check_legacy_checkpoint_path)
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.parallel import make_mesh

    # the code is cataloged (append-only contract, docs/ANALYSIS.md)
    assert CODES["GL007"][0] == Sev.WARNING
    diags = check_legacy_checkpoint_path("Trainer", where="here")
    assert [d.code for d in diags] == ["GL007"]
    assert "save_states" in diags[0].message
    assert "checkpoint" in diags[0].hint

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="tanh"), nn.Dense(8))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, 8)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = trainer.make_fused_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                   mesh=make_mesh({"dp": 8}), zero=1,
                                   lint="warn")
    x = nd.array(np.random.RandomState(0).rand(8, 8).astype(np.float32))
    y = nd.array((np.arange(8) % 4).astype(np.float32))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        step(x, y)
    assert any("GL007" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]
    with pytest.raises(RuntimeError, match="save_checkpoint"):
        trainer.save_states("/tmp/should_not_exist.states")
    with pytest.raises(RuntimeError, match="restore_checkpoint"):
        trainer.load_states("/tmp/should_not_exist.states")
    # a plain (zero=0) fused-step Trainer keeps the legacy path
    trainer2 = gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 0.1})
    trainer2.make_fused_step(net, gluon.loss.SoftmaxCrossEntropyLoss())
    trainer2.save_states("/tmp/gl007_plain.states")
    os.unlink("/tmp/gl007_plain.states")


def test_gl012_unbounded_silent_skip_streak():
    """GL012 gate: nonfinite='skip' under a STATIC loss scale with no
    skip-streak bound warns (an unbounded silent skip-streak is a
    stalled run that looks alive); a dynamic scale or a declared
    skip_streak_budget silences it.  The live enforcement — the
    supervisor's divergence verdict at the declared budget — lives in
    tests/test_supervisor.py."""
    import warnings

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.analysis import (CODES, Severity as Sev,
                                              check_unbounded_skip)
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.parallel import make_train_step

    # the code is cataloged (append-only contract, docs/ANALYSIS.md)
    assert CODES["GL012"][0] == Sev.WARNING
    diags = check_unbounded_skip("skip", False, None, where="here")
    assert [d.code for d in diags] == ["GL012"]
    assert "static loss scale" in diags[0].message
    assert "dynamic" in diags[0].hint and \
        "skip_streak_budget" in diags[0].hint
    # every bounded configuration is clean
    assert check_unbounded_skip("skip", True, None) == []     # dynamic
    assert check_unbounded_skip("skip", False, 16) == []      # budget
    assert check_unbounded_skip("raise", False, None) == []   # loud
    assert check_unbounded_skip("off", False, None) == []

    def build(**kw):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="tanh"), nn.Dense(4))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 8)))
        return make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                               optimizer="sgd", learning_rate=0.1,
                               lint="warn", **kw)

    x = nd.array(np.random.RandomState(0).rand(4, 8).astype(np.float32))
    y = nd.array((np.arange(4) % 4).astype(np.float32))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build(nonfinite="skip", loss_scale=1024.0)(x, y)
    assert any("GL012" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build(nonfinite="skip", loss_scale=1024.0,
              skip_streak_budget=8)(x, y)
    assert not any("GL012" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]


def test_gl010_inference_param_donation():
    """GL010 gate: the check names overlapping param leaves as an
    error; disjoint donation (cache/input argnums) is clean.  The
    engine-level integration — ``ServeEngine(donate_argnums=(0,))``
    refused at trace time — lives in tests/test_serve.py."""
    from incubator_mxnet_tpu.analysis import (
        CODES, Severity as Sev, check_inference_param_donation)

    # the code is cataloged (append-only contract, docs/ANALYSIS.md)
    assert CODES["GL010"][0] == Sev.ERROR
    diags = check_inference_param_donation([0, 1, 5], range(4),
                                           where="ServeEngine(net)")
    assert [d.code for d in diags] == ["GL010"]
    assert diags[0].severity == Sev.ERROR
    assert "[0, 1]" in diags[0].message
    assert "decode cache" in diags[0].hint
    # donated per-request state outside the param leaves is the
    # LEGITIMATE pattern (serve/cache.py donates the cache argnum)
    assert check_inference_param_donation([5, 6], range(4)) == []
    assert check_inference_param_donation([], range(4)) == []


def test_gl011_swap_compatibility():
    """GL011 gate: shape/dtype/tree drift between the served param
    signature and a hot-swap candidate is an aggregated error; an
    identical candidate is clean.  The engine-level integration —
    ``ServeEngine.update_params`` refusing a drifted candidate before
    staging anything — lives in tests/test_serve_resilience.py."""
    import numpy as np

    from incubator_mxnet_tpu.analysis import (
        CODES, Severity as Sev, check_swap_compatibility)

    # the code is cataloged (append-only contract, docs/ANALYSIS.md)
    assert CODES["GL011"][0] == Sev.ERROR
    served = [("w", (4, 4), np.dtype(np.float32)),
              ("b", (4,), np.dtype(np.float32))]
    # identical candidate: clean
    assert check_swap_compatibility(served, list(served)) == []
    # shape + dtype drift: ONE aggregated error naming both
    cand = [("w", (4, 5), np.dtype(np.float32)),
            ("b", (4,), np.dtype(np.float64))]
    diags = check_swap_compatibility(served, cand, where="update_params")
    assert [d.code for d in diags] == ["GL011"]
    assert diags[0].severity == Sev.ERROR
    assert "shape (4, 4) -> (4, 5)" in diags[0].message
    assert "dtype float32 -> float64" in diags[0].message
    assert "recompile" in diags[0].message
    assert "param_signature" in diags[0].hint
    # tree drift: missing + foreign names
    diags = check_swap_compatibility(served, list(served),
                                     missing=("b",), extra=("c",))
    assert len(diags) == 1 and "missing from candidate" in diags[0].message
    assert "not in the served tree" in diags[0].message
    # tree drift: raw length mismatch is NEVER zip-truncated to clean
    diags = check_swap_compatibility(served, served[:1])
    assert len(diags) == 1 and "param count 2 -> 1" in diags[0].message


def test_gl014_ungated_promotion_swap_runtime():
    """GL014 gate (runtime sightline): a self-identified promotion/
    daemon swap (``context=``) with neither canary rows nor a
    ``canary_tol`` warns — the only gate left is the zeros canary's
    finiteness check, which a finite-but-wrong candidate passes.  Any
    declared gate, or an interactive (context-free) swap, is clean."""
    import warnings

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.analysis import (CODES, Severity as Sev,
                                              check_ungated_swap)
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.serve import ServeEngine

    # the code is cataloged (append-only contract, docs/ANALYSIS.md)
    assert CODES["GL014"][0] == Sev.WARNING
    diags = check_ungated_swap(None, None, context="promotion",
                               where="here")
    assert [d.code for d in diags] == ["GL014"]
    assert "promotion" in diags[0].message
    assert "canary" in diags[0].hint
    # any declared gate, or no daemon context, is clean
    assert check_ungated_swap(np.zeros((1, 4)), None,
                              context="promotion") == []
    assert check_ungated_swap(None, 0.5, context="promotion") == []
    assert check_ungated_swap(None, None, context=None) == []

    def build(**kw):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="tanh"), nn.Dense(4))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 8)))
        eng = ServeEngine(net, buckets=(4,), lint="warn", **kw)
        eng.warmup(np.zeros((8,), np.float32))
        return eng

    eng = build()
    cand = [np.array(p._data._data) for p in eng._params]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng.update_params([np.array(a) for a in cand], context="daemon")
    assert any("GL014" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]
    # gated daemon swap: no warning
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng.update_params([np.array(a) for a in cand], canary_tol=10.0,
                          context="daemon")
    assert not any("GL014" in str(w.message) for w in caught)
    # suppression is honored
    eng2 = build(lint_suppress=("GL014",))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng2.update_params([np.array(a) for a in cand],
                           context="daemon")
    assert not any("GL014" in str(w.message) for w in caught)


def test_gl014_source_rule_promotion_name_stack():
    """GL014 gate (source sightline): a bare ``update_params(...)``
    inside a def/class whose name smells like a promotion/daemon path
    is flagged; passing either canary gate — or living outside such a
    scope — is clean, and inline suppression works."""
    from incubator_mxnet_tpu.analysis import check_promotion_swap_ungated

    flagged = _lint("""
        class PromotionDaemon:
            def evaluate(self, engine, raw):
                engine.update_params(raw)
    """)
    assert [d.code for d in flagged] == ["GL014"]
    assert "PromotionDaemon.evaluate" in flagged[0].message
    # either gate kwarg bound to a non-None value is gated
    assert _lint("""
        def flywheel_tick(engine, raw, rows):
            engine.update_params(raw, canary=rows)
    """) == []
    assert _lint("""
        def daemon_poll(engine, raw):
            engine.update_params(raw, canary_tol=4.0)
    """) == []
    # a positional canary and opaque **kwargs both count as gated
    assert _lint("""
        def promote(engine, raw, rows):
            engine.update_params(raw, rows)
    """) == []
    assert _lint("""
        def promote(engine, raw, **kw):
            engine.update_params(raw, **kw)
    """) == []
    # canary=None explicitly is NOT a gate
    assert [d.code for d in _lint("""
        def promote(engine, raw):
            engine.update_params(raw, canary=None)
    """)] == ["GL014"]
    # outside a promotion-scented scope: clean (interactive swap)
    assert _lint("""
        def handle_reload(engine, raw):
            engine.update_params(raw)
    """) == []
    # inline suppression
    assert _lint("""
        def promote(engine, raw):
            engine.update_params(raw)  # graftlint: disable=GL014
    """) == []
    # the standalone checker agrees with the lint_source integration
    diags = check_promotion_swap_ungated(
        "class Promoter:\n"
        "    def run(self, e, raw):\n"
        "        e.update_params(raw)\n", path="fly.py")
    assert [d.code for d in diags] == ["GL014"]
    assert diags[0].where == "fly.py:3"


def test_cli_reports_with_location(tmp_path, capsys):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import graftlint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\nfrom jax import shard_map\n")
    rc = graftlint.main([str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "bad.py:2" in out and "GL101" in out


# ---------------------------------------------------------------------------
# --format=json + prefix globs (stable machine schema for CI/autotuner)
# ---------------------------------------------------------------------------

def _tools_import(name):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_cli_json_format_stable_schema(tmp_path, capsys):
    import json

    graftlint = _tools_import("graftlint")
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import shard_map\n"
                   "from jax.sharding import PartitionSpec as P\n"
                   "s = P(0)\n")  # GL101 + GL103
    rc = graftlint.main([str(bad), "--format", "json"])
    obj = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert obj["version"] == 1 and obj["tool"] == "graftlint"
    assert obj["summary"]["errors"] == 2 and obj["summary"]["total"] == 2
    codes = sorted(f["code"] for f in obj["findings"])
    assert codes == ["GL101", "GL103"]
    for f in obj["findings"]:
        # the stable Diagnostic schema: severity serialized by NAME
        assert set(f) == {"code", "severity", "message", "where", "hint"}
        assert f["severity"] == "error"
        assert "bad.py" in f["where"]
    # clean run: empty findings, exit 0, still valid JSON
    rc = graftlint.main([os.path.join(ROOT, "incubator_mxnet_tpu",
                                      "analysis"), "--format", "json"])
    obj = json.loads(capsys.readouterr().out)
    assert rc == 0 and obj["findings"] == []


def test_cli_select_ignore_prefix_globs(tmp_path):
    graftlint = _tools_import("graftlint")
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import shard_map\n"
                   "from jax.sharding import PartitionSpec as P\n"
                   "s = P(0)\n")  # GL101 + GL103
    # GL1* selects both -> still errors
    assert graftlint.main([str(bad), "--select", "GL1*"]) == 1
    # GL2* selects neither -> clean
    assert graftlint.main([str(bad), "--select", "GL2*"]) == 0
    # ignoring the whole GL1xx family silences the gate
    assert graftlint.main([str(bad), "--ignore", "GL1*"]) == 0
    # --suppress alias takes globs too
    assert graftlint.main([str(bad), "--suppress", "GL10*"]) == 0


def test_lint_suppress_accepts_globs():
    """make_train_step(lint_suppress=("GL2*",)) and LintReport share
    the same glob grammar as the CLI filters."""
    from incubator_mxnet_tpu.analysis import (Diagnostic, LintReport,
                                              Severity as Sev)

    rep = LintReport(suppress=("GL00?", "GL2*"))
    rep.add(Diagnostic("GL002", Sev.ERROR, "a"))
    rep.add(Diagnostic("GL203", Sev.WARNING, "b"))
    rep.add(Diagnostic("GL101", Sev.ERROR, "c"))
    assert [d.code for d in rep] == ["GL101"]
    assert sorted(d.code for d in rep.suppressed) == ["GL002", "GL203"]


# ---------------------------------------------------------------------------
# graftcost CLI gate (CI: feasible -> 0, infeasible budget -> 1, JSON
# parses against the schema)
# ---------------------------------------------------------------------------

def test_graftcost_cli_gate_and_json(capsys):
    import json

    graftcost = _tools_import("graftcost")
    # feasible: the dense test net fits any real device -> exit 0
    assert graftcost.main(["--model", "dense", "--batch", "16"]) == 0
    capsys.readouterr()
    # infeasible --hbm-budget: GL201 -> exit 1
    assert graftcost.main(["--model", "dense", "--batch", "16",
                           "--hbm-budget", "1KiB"]) == 1
    out = capsys.readouterr().out
    assert "GL201" in out
    # JSON output parses against the CostReport schema
    assert graftcost.main(["--model", "dense", "--batch", "16",
                           "--format", "json"]) == 0
    obj = json.loads(capsys.readouterr().out)
    assert obj["version"] == 1
    for key in ("device", "categories", "totals", "peak_bytes",
                "opt_state_bytes", "comm", "roofline", "diagnostics"):
        assert key in obj, key
    assert obj["totals"]["hbm_bytes"] > 0
    assert obj["categories"]["conv"]["flops"] > 0
    assert set(obj["roofline"]) == {"compute_s", "hbm_s", "comm_s",
                                    "step_s"}
    # diagnostics ride the same stable Diagnostic schema
    assert graftcost.main(["--model", "dense", "--batch", "16",
                           "--hbm-budget", "1KiB", "--format",
                           "json"]) == 1
    obj = json.loads(capsys.readouterr().out)
    codes = [d["code"] for d in obj["diagnostics"]]
    assert "GL201" in codes
    for d in obj["diagnostics"]:
        assert set(d) == {"code", "severity", "message", "where", "hint"}


# ---------------------------------------------------------------------------
# --format=sarif (SARIF 2.1.0 for CI code-scanning UIs)
# ---------------------------------------------------------------------------

def _validate_sarif_2_1_0(log):
    """Structural validation against the SARIF 2.1.0 schema's required
    shape (no jsonschema dependency in the image: the invariants below
    ARE the schema's required properties for log/run/tool/driver/
    result/location objects)."""
    assert set(log) >= {"version", "runs"}
    assert log["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in log.get("$schema", "")
    assert isinstance(log["runs"], list) and log["runs"]
    for run in log["runs"]:
        assert "tool" in run and "driver" in run["tool"]
        driver = run["tool"]["driver"]
        assert isinstance(driver.get("name"), str) and driver["name"]
        rules = driver.get("rules", [])
        rule_ids = []
        for rule in rules:
            assert isinstance(rule["id"], str)
            assert "text" in rule.get("shortDescription", {})
            assert rule.get("defaultConfiguration", {}).get("level") \
                in ("none", "note", "warning", "error")
            rule_ids.append(rule["id"])
        for res in run.get("results", []):
            assert isinstance(res["message"]["text"], str) \
                and res["message"]["text"]
            assert res.get("level") in ("none", "note", "warning",
                                        "error")
            assert res["ruleId"] in rule_ids
            assert rules[res["ruleIndex"]]["id"] == res["ruleId"]
            for loc in res.get("locations", []):
                phys = loc["physicalLocation"]
                assert isinstance(
                    phys["artifactLocation"]["uri"], str)
                assert phys["region"]["startLine"] >= 1


def test_cli_sarif_format(tmp_path, capsys):
    import json

    graftlint = _tools_import("graftlint")
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import shard_map\n"
                   "from jax.sharding import PartitionSpec as P\n"
                   "s = P(0)\n")  # GL101 + GL103
    rc = graftlint.main([str(bad), "--format", "sarif"])
    log = json.loads(capsys.readouterr().out)
    assert rc == 1
    _validate_sarif_2_1_0(log)
    results = log["runs"][0]["results"]
    assert sorted(r["ruleId"] for r in results) == ["GL101", "GL103"]
    assert all(r["level"] == "error" for r in results)
    # source findings carry a physical location with the right line
    gl101 = next(r for r in results if r["ruleId"] == "GL101")
    phys = gl101["locations"][0]["physicalLocation"]
    assert phys["artifactLocation"]["uri"].endswith("bad.py")
    assert phys["region"]["startLine"] == 1
    # rules metadata comes from the stable catalog
    rules = {r["id"]: r for r in log["runs"][0]["tool"]["driver"]["rules"]}
    assert "shard_map" in rules["GL101"]["shortDescription"]["text"]
    # a clean run is a valid SARIF log with zero results, exit 0
    rc = graftlint.main([os.path.join(ROOT, "incubator_mxnet_tpu",
                                      "analysis"), "--format", "sarif"])
    log = json.loads(capsys.readouterr().out)
    assert rc == 0
    _validate_sarif_2_1_0(log)
    assert log["runs"][0]["results"] == []


# ---------------------------------------------------------------------------
# GL304 — zero-site pass composition (graftsched, docs/ANALYSIS.md)
# ---------------------------------------------------------------------------

def test_gl304_cataloged():
    from incubator_mxnet_tpu.analysis import CODES

    sev, text = CODES["GL304"]
    assert sev == Severity.WARNING
    assert "zero sites" in text


def test_gl304_fires_on_zero_site_pass():
    """A pass named in passes= that matches nothing in the program is a
    silent no-op — GL304 warns; an explicitly schedule-disabled pass is
    a deliberate decision and stays quiet."""
    import warnings

    import numpy as np

    import jax

    from incubator_mxnet_tpu.analysis.passes import (PassContext,
                                                     PassManager,
                                                     PassSchedule)

    cj = jax.make_jaxpr(lambda a, b: a @ b)(
        jax.ShapeDtypeStruct((8, 8), np.float32),
        jax.ShapeDtypeStruct((8, 8), np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = PassManager(["space_to_depth"],
                          raise_on_error=False).run(cj, PassContext())
    assert any(d.code == "GL304" for d in res.diagnostics)
    assert any("GL304" in str(x.message) for x in w)
    assert not res.receipts[0].installed  # still a clean no-op
    # disabled-by-schedule: no GL304 (the decision is on the record)
    sched = PassSchedule([("space_to_depth", False)])
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = PassManager(None, schedule=sched,
                          raise_on_error=False).run(cj, PassContext())
    assert not any(d.code == "GL304" for d in res.diagnostics)
    assert "disabled by schedule" in (res.receipts[0].notes or "")


def test_gl304_rides_graftpass_cli_without_gating(capsys):
    """GL304 is a WARNING: it lands in the CLI diagnostics but never
    flips the exit code."""
    import json

    import pytest as _pytest

    import tools.graftpass as gp

    with _pytest.warns(UserWarning, match="GL304"):
        rc = gp.main(["--model", "dense", "--passes", "space_to_depth",
                      "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert any(d["code"] == "GL304" for d in out["diagnostics"])


# ---------------------------------------------------------------------------
# graftpass --schedule / --list-sites / --format sarif (graftsched CLI)
# ---------------------------------------------------------------------------

def test_graftpass_cli_list_sites(capsys):
    import json

    import tools.graftpass as gp

    rc = gp.main(["--model", "dense",
                  "--passes", "amp_bf16,quantize_int8,cse_dead_aux",
                  "--list-sites", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    by_pass = {}
    for r in out["sites"]:
        by_pass.setdefault(r["pass"], []).append(r)
    assert [r["site"] for r in by_pass["amp_bf16"]] == ["dot_general:0",
                                                        "dot_general:1"]
    assert all(r["site"].startswith("invar:")
               for r in by_pass["quantize_int8"])
    # whole-program passes report exactly that, not an empty listing
    assert by_pass["cse_dead_aux"][0]["site"] is None
    assert by_pass["cse_dead_aux"][0]["kind"] == "whole-program"


def test_graftpass_cli_schedule_decisions_and_receipts(tmp_path, capsys):
    import json

    import tools.graftpass as gp
    from incubator_mxnet_tpu.analysis.passes import PassSchedule

    sched = PassSchedule([("amp_bf16", {"dot_general:0": True,
                                        "dot_general:1": False})])
    f = tmp_path / "sched.json"
    f.write_text(sched.to_json())
    rc = gp.main(["--model", "dense", "--schedule", str(f),
                  "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["schedule"]["hash"] == sched.hash()
    (amp,) = out["passes"]
    rows = {r["site"]: r for r in amp["sites"]}
    assert rows["dot_general:0"]["decision"] is True
    assert rows["dot_general:0"]["installed"] is True
    assert rows["dot_general:1"]["decision"] is False
    assert rows["dot_general:1"]["installed"] is False
    # a malformed schedule file is a usage error, not a crash
    bad = tmp_path / "bad.json"
    bad.write_text("{\"nope\": 1}")
    assert gp.main(["--model", "dense", "--schedule", str(bad)]) == 2


def test_graftpass_cli_schedule_exit_1_on_refused_site(tmp_path, capsys):
    """A schedule enabling a GL301-refused rewrite exits 1 — the CI
    gate shape."""
    import json

    import pytest as _pytest

    import tools.graftpass as gp
    from incubator_mxnet_tpu.analysis.passes import (PASS_REGISTRY,
                                                     PassSchedule,
                                                     register_pass)
    from tests.test_passes import _ValueBreaker

    register_pass("_test_sched_breaker", _ValueBreaker())
    try:
        f = tmp_path / "sched.json"
        f.write_text(PassSchedule(
            [("_test_sched_breaker", True)]).to_json())
        with _pytest.warns(UserWarning, match="GL301"):
            rc = gp.main(["--model", "dense", "--schedule", str(f),
                          "--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert any(d["code"] == "GL301" for d in out["diagnostics"])
    finally:
        PASS_REGISTRY.pop("_test_sched_breaker", None)


def test_graftpass_cli_sarif_format(capsys):
    import json

    import pytest as _pytest

    import tools.graftpass as gp

    with _pytest.warns(UserWarning, match="GL304"):
        rc = gp.main(["--model", "dense", "--passes", "space_to_depth",
                      "--format", "sarif"])
    log = json.loads(capsys.readouterr().out)
    assert rc == 0
    _validate_sarif_2_1_0(log)
    results = log["runs"][0]["results"]
    assert any(r["ruleId"] == "GL304" and r["level"] == "warning"
               for r in results)


def test_gl013_unsaved_compressor_residual():
    """GL013 gate: error-feedback compression on a sync='allreduce'
    step warns (the residual can never reach the checkpoint save set,
    so kill-and-resume silently drops the bank); the async rungs —
    whose param_service checkpoint subtree carries the compressor's
    state — are clean, as is no compression at all.  The resume-path
    enforcement (bit-identical tail through CheckpointManager) lives in
    tests/test_param_service.py."""
    import warnings

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.analysis import (CODES, Severity as Sev,
                                              check_unsaved_compressor_state)
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.kvstore.gradient_compression import (
        Int8Compressor, make_compressor)
    from incubator_mxnet_tpu.parallel import make_train_step

    # the code is cataloged (append-only contract, docs/ANALYSIS.md)
    assert CODES["GL013"][0] == Sev.WARNING
    comp = make_compressor("topk")
    diags = check_unsaved_compressor_state(comp, "allreduce", where="here")
    assert [d.code for d in diags] == ["GL013"]
    assert "'topk'" in diags[0].message
    assert "sync='async'" in diags[0].hint
    # every safe configuration is clean
    assert check_unsaved_compressor_state(None, "allreduce") == []
    assert check_unsaved_compressor_state(comp, "async") == []
    assert check_unsaved_compressor_state(comp, "auto") == []
    assert check_unsaved_compressor_state(Int8Compressor(), "auto") == []

    def build(**kw):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="tanh"), nn.Dense(4))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 8)))
        return make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                               optimizer="sgd", learning_rate=0.1,
                               lint="warn", **kw)

    x = nd.array(np.random.RandomState(0).rand(4, 8).astype(np.float32))
    y = nd.array((np.arange(4) % 4).astype(np.float32))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build(compression="int8")(x, y)
    assert any("GL013" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build(compression="int8", sync="async")(x, y)
    assert not any("GL013" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]
    # lint_suppress opts out, like every other code
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build(compression="int8", lint_suppress=("GL013",))(x, y)
    assert not any("GL013" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]
