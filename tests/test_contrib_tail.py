"""Gradient compression, contrib.text, SVRG tests (models:
tests/nightly/dist_sync_kvstore.py 2-bit checks,
tests/python/unittest/test_contrib_text.py, test_contrib_svrg_module.py)."""
import collections

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.contrib import text
from incubator_mxnet_tpu.contrib.svrg_optimization import SVRGModule
from incubator_mxnet_tpu.kvstore.gradient_compression import \
    GradientCompression


# -------------------------------------------------------- 2-bit compression

def test_two_bit_ternary_values():
    import jax.numpy as jnp
    gc = GradientCompression(threshold=0.5)
    g = jnp.asarray([0.3, 0.7, -0.9, 0.0, -0.2])
    q = gc.compress("k", g)
    np.testing.assert_allclose(np.asarray(q), [0.0, 0.5, -0.5, 0.0, 0.0])
    # residual = g - q
    np.testing.assert_allclose(np.asarray(gc._residual["k"]),
                               [0.3, 0.2, -0.4, 0.0, -0.2], atol=1e-6)


def test_two_bit_error_feedback_converges():
    """Repeated compression of a constant gradient transmits the full
    magnitude over time (unbiasedness via residual accumulation)."""
    import jax.numpy as jnp
    gc = GradientCompression(threshold=0.5)
    g = jnp.asarray([0.2, -0.3])
    total = np.zeros(2)
    for _ in range(10):
        total += np.asarray(gc.compress("k", g))
    np.testing.assert_allclose(total, [2.0, -3.0], atol=0.51)


def test_kvstore_compression_integration():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    kv.init("w", nd.zeros((3,)))
    kv.push("w", nd.array(np.array([2.0, 0.3, -1.5], np.float32)))
    out = nd.zeros((3,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), [1.0, 0.0, -1.0])


# -------------------------------------------------------------- contrib.text

def test_vocabulary_basic():
    counter = collections.Counter(
        ["the", "the", "the", "cat", "cat", "dog"])
    vocab = text.Vocabulary(counter, min_freq=1, unknown_token="<unk>",
                            reserved_tokens=["<pad>"])
    assert vocab.to_indices("the") == vocab.token_to_idx["the"]
    assert vocab.to_indices(["the", "cat"]) == [
        vocab.token_to_idx["the"], vocab.token_to_idx["cat"]]
    # unknown maps to index of <unk> (0)
    assert vocab.to_indices("unicorn") == vocab.token_to_idx["<unk>"]
    assert vocab.to_tokens(vocab.to_indices("dog")) == "dog"
    assert len(vocab) == 5  # unk, pad, the, cat, dog


def test_vocabulary_most_freq_and_min_freq():
    counter = collections.Counter(
        {"a": 5, "b": 4, "c": 3, "d": 2, "e": 1})
    vocab = text.Vocabulary(counter, most_freq_count=2, min_freq=2)
    assert "a" in vocab.token_to_idx and "b" in vocab.token_to_idx
    assert "c" not in vocab.token_to_idx


def test_custom_embedding(tmp_path):
    path = str(tmp_path / "emb.txt")
    with open(path, "w") as f:
        f.write("hello 1.0 2.0 3.0\n")
        f.write("world 4.0 5.0 6.0\n")
    emb = text.embedding.CustomEmbedding(path)
    assert emb.vec_len == 3
    v = emb.get_vecs_by_tokens("world")
    np.testing.assert_allclose(v.asnumpy(), [4.0, 5.0, 6.0])
    vs = emb.get_vecs_by_tokens(["hello", "nope"])
    np.testing.assert_allclose(vs.asnumpy()[1], 0.0)  # unknown → zeros
    emb.update_token_vectors("hello", nd.array(np.array([9., 9., 9.])))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), 9.0)


def test_count_tokens():
    counter = text.utils.count_tokens_from_str("a b b\nc a  a", to_lower=True)
    assert counter["a"] == 3 and counter["b"] == 2 and counter["c"] == 1


# --------------------------------------------------------------------- SVRG

def test_svrg_module_convergence():
    """SVRG on least squares converges (model:
    test_contrib_svrg_module.py test_svrg_with_sgd)."""
    rng = np.random.RandomState(0)
    n, d = 64, 4
    w_true = rng.uniform(-1, 1, (1, d)).astype(np.float32)
    x = rng.uniform(-1, 1, (n, d)).astype(np.float32)
    y = (x @ w_true.T).reshape(-1)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("lin_label")
    fc = mx.sym.FullyConnected(data, num_hidden=1, no_bias=True, name="fc")
    out = mx.sym.LinearRegressionOutput(fc, label, name="lin")

    it = mx.io.NDArrayIter(data={"data": x}, label={"lin_label": y},
                           batch_size=16, label_name="lin_label")
    mod = SVRGModule(out, data_names=("data",), label_names=("lin_label",),
                     update_freq=2)
    mod.fit(it, eval_metric="mse", optimizer="sgd",
            optimizer_params={"learning_rate": 0.2}, num_epoch=16)
    w = mod.get_params()[0]["fc_weight"].asnumpy()
    np.testing.assert_allclose(w, w_true, atol=0.1)
