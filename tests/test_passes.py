"""graftpass: the verified trace-time jaxpr→jaxpr rewrite engine
(analysis/passes.py, docs/PASSES.md, GL301–GL303 in docs/ANALYSIS.md).

The acceptance surface of ROADMAP item 5:

- a contract-violating pass trips GL301 and is NOT installed — refused
  at trace time with zero compiles spent (train step and manager);
- a pass that introduces a graftlint finding trips the GL302 re-lint
  gate and is refused;
- quantize / AMP / space-to-depth / CSE golden parity on the dense MLP,
  the conv stem and the fused train step (dp-mesh leg under
  ``lint="error"`` + ``cost="check"``);
- cost receipts: predicted HBM bytes strictly drop for space_to_depth
  and cse_dead_aux; param bytes drop ~4x for quantize_int8;
- the ServeEngine int8 tier rides the pass path: ``dtype="int8"`` ==
  ``passes=("quantize_int8",)`` bitwise, with 0 post-warmup recompiles;
- the autotuner ranks pass on/off knobs and rejects GL301 pipelines
  with zero compiles;
- the tools/graftpass.py CLI gate (exit 1 on contract violation).

Budget discipline: tiny nets, no mesh wider than 8 forged CPU devices,
heavy soaks stay out (the suite is at its 870 s ceiling).
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.analysis import CODES, LintError, Severity
from incubator_mxnet_tpu.analysis.passes import (Contract, GraftPass,
                                                 PASS_REGISTRY,
                                                 PassContext, PassManager,
                                                 PassResult, _default_bind,
                                                 get_pass, register_pass,
                                                 resolve_passes, retrace)
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import aot, make_mesh, make_train_step
from incubator_mxnet_tpu.serve import ServeEngine

SAMPLE = (16,)


def _mlp(seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2,) + SAMPLE))
    return net


def _dense_step(passes=None, seed=3, mesh=None, **kw):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    for _ in range(3):
        net.add(nn.Dense(16, activation="tanh"))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, 16)))
    return make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                           optimizer="sgd", learning_rate=0.1, momentum=0.9,
                           mesh=mesh, passes=passes, **kw)


def _batch(b=16):
    rng = np.random.RandomState(0)
    return (nd.array(rng.rand(b, 16).astype(np.float32)),
            nd.array((np.arange(b) % 4).astype(np.float32)))


class _ValueBreaker(GraftPass):
    """Deliberately wrong rewrite: perturbs every matmul output — must
    trip GL301 on the concrete probe under any contract."""

    name = "_test_value_breaker"
    contract = Contract.bit_exact()

    def run(self, closed, ctx):
        hits = [0]

        def rule(eqn, invals):
            if eqn.primitive.name == "dot_general":
                hits[0] += 1
                return [o * 1.001 for o in _default_bind(eqn, invals)]
            return None

        new = retrace(closed, rule)
        return PassResult(new, hits=hits[0])


# ---------------------------------------------------------------------------
# catalog, registry, resolution
# ---------------------------------------------------------------------------

def test_gl3xx_cataloged():
    assert CODES["GL301"][0] == Severity.ERROR
    assert CODES["GL302"][0] == Severity.ERROR
    assert CODES["GL303"][0] == Severity.WARNING


def test_registry_and_resolution(monkeypatch):
    for name in ("quantize_int8", "quantize_int4", "amp_bf16",
                 "space_to_depth", "cse_dead_aux"):
        assert name in PASS_REGISTRY
        assert get_pass(name).name == name
    assert resolve_passes("cse_dead_aux, amp_bf16")[1].name == "amp_bf16"
    assert resolve_passes(()) == ()
    with pytest.raises(ValueError, match="unknown graftpass"):
        get_pass("fuse_everything")
    # env resolution: explicit arg > MXTPU_PASSES > ()
    monkeypatch.setenv("MXTPU_PASSES", "cse_dead_aux")
    s = _dense_step(lint="off")
    assert [p.name for p in s._passes] == ["cse_dead_aux"]
    s2 = _dense_step(passes=(), lint="off")
    assert s2._passes == ()
    monkeypatch.delenv("MXTPU_PASSES")
    assert _dense_step(lint="off")._passes == ()


def test_contract_check_semantics():
    a = np.array([[1.0, 2.0, 3.0]], np.float32)
    ok, d = Contract.bit_exact().check([a], [a.copy()])
    assert ok and d["bitwise"]
    ok, _ = Contract.bit_exact().check([a], [a + 1e-7])
    assert not ok
    ok, d = Contract.tolerance(0.1).check([a], [a + 0.2])
    assert ok and d["max_abs_err"] == pytest.approx(0.2)
    ok, _ = Contract.tolerance(0.01).check([a], [a + 0.2])
    assert not ok
    # argmax: decided rankings must hold; within-margin ties may flip
    ref = np.array([[0.0, 1.0], [0.0, 0.001]], np.float32)
    flip_tie = np.array([[0.0, 1.0], [0.001, 0.0]], np.float32)
    ok, d = Contract.argmax_preserving(0.05).check([ref], [flip_tie])
    assert ok and d["argmax_rows_checked"] == 1
    flip_decided = np.array([[1.0, 0.0], [0.0, 0.001]], np.float32)
    ok, _ = Contract.argmax_preserving(0.05).check([ref], [flip_decided])
    assert not ok


# ---------------------------------------------------------------------------
# the four shipped passes, at the manager level
# ---------------------------------------------------------------------------

def test_cse_dead_aux_merges_and_drops_with_receipts():
    def f(x, w):
        m1 = jnp.mean(x)
        m2 = jnp.mean(x)            # duplicate of m1
        _dead = (x @ w) @ w.T       # dead MXU work, noqa: F841
        return (x - m1) * m2

    cj = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((128, 128), jnp.float32),
                           jax.ShapeDtypeStruct((128, 128), jnp.float32))
    res = PassManager(["cse_dead_aux"]).run(cj, PassContext())
    r = res.receipts[0]
    assert r.installed and r.hits >= 2
    assert r.hbm_bytes_after < r.hbm_bytes_before   # strict drop
    assert r.probe["bitwise"] is True
    assert res.changed
    # round-trips through the stable JSON schema
    json.dumps([q.to_dict() for q in res.receipts])


def test_space_to_depth_bit_exact_and_bytes_drop():
    from jax import lax

    def conv1(x, w):
        return lax.conv_general_dilated(
            x, w, (2, 2), [(3, 3), (3, 3)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    cj = jax.make_jaxpr(conv1)(
        jax.ShapeDtypeStruct((2, 3, 16, 16), jnp.float32),
        jax.ShapeDtypeStruct((8, 3, 7, 7), jnp.float32))
    res = PassManager(["space_to_depth"]).run(cj, PassContext())
    r = res.receipts[0]
    assert r.installed and r.hits == 1
    assert r.probe["bitwise"] is True          # the bit_exact contract
    assert r.hbm_bytes_after < r.hbm_bytes_before   # strict drop
    assert r.flops_after < r.flops_before      # lane padding removed
    # golden parity on real floats (reassociation-level only)
    rng = np.random.RandomState(0)
    xv = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
    wv = rng.normal(size=(8, 3, 7, 7)).astype(np.float32)
    from incubator_mxnet_tpu.analysis.passes import eval_closed

    ref = np.asarray(eval_closed(cj, [xv, wv])[0])
    got = np.asarray(eval_closed(res.closed_jaxpr, [xv, wv])[0])
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-5)

    # a stride-1 conv is not a target: the pass reports nothing to do
    def conv_s1(x, w):
        return lax.conv_general_dilated(
            x, w, (1, 1), [(3, 3), (3, 3)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    cj1 = jax.make_jaxpr(conv_s1)(
        jax.ShapeDtypeStruct((2, 3, 16, 16), jnp.float32),
        jax.ShapeDtypeStruct((8, 3, 7, 7), jnp.float32))
    res1 = PassManager(["space_to_depth"]).run(cj1, PassContext())
    assert not res1.changed and not res1.receipts[0].changed


def test_maxpool_bwd_mask_bit_exact_and_wrong_mask_refused():
    """ISSUE 14 lever (c): the select-and-scatter max-pool backward
    becomes the shifted-window first-argmax mask — BIT-exact vs XLA's
    own gradient (first-argmax IS the GE-select tie rule; the dyadic
    probe is full of exact ties, the hard case), predicted bytes drop,
    and a deliberately-wrong mask (winner index shifted by one) is
    refused by the GL301 probe with zero compiles spent."""
    from jax import lax

    from incubator_mxnet_tpu.analysis.passes import (MaxPoolBwdMaskPass,
                                                     eval_closed)
    from incubator_mxnet_tpu.parallel import aot

    def mp_loss(x):
        y = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 3, 3),
                              (1, 1, 2, 2),
                              ((0, 0), (0, 0), (1, 1), (1, 1)))
        return (y * 1.5).sum()

    cj = jax.make_jaxpr(jax.grad(mp_loss))(
        jax.ShapeDtypeStruct((2, 4, 9, 9), jnp.float32))
    assert any(e.primitive.name == "select_and_scatter_add"
               for e in cj.jaxpr.eqns), "precondition: the scatter form"
    res = PassManager(["maxpool_bwd_mask"]).run(cj, PassContext())
    r = res.receipts[0]
    assert r.installed and r.hits == 1
    assert r.probe["bitwise"] is True          # bit_exact incl. ties
    assert r.hbm_bytes_after < r.hbm_bytes_before
    assert not any(e.primitive.name == "select_and_scatter_add"
                   for e in res.closed_jaxpr.jaxpr.eqns)
    # golden parity on real floats WITH post-ReLU-style zero plateaus
    # (tie-heavy): first-argmax routing must match jax's gradient
    rng = np.random.RandomState(0)
    xv = np.maximum(rng.normal(size=(2, 4, 9, 9)), 0.0).astype(np.float32)
    ref = np.asarray(eval_closed(cj, [xv])[0])
    got = np.asarray(eval_closed(res.closed_jaxpr, [xv])[0])
    np.testing.assert_array_equal(got, ref)

    # the deliberately-wrong mask: winner index shifted by one — the
    # GL301 contract probe refuses it, zero compiles spent
    bad = MaxPoolBwdMaskPass()
    bad._shift_mask = 1
    before = aot.XLA_COMPILES.count
    with pytest.raises(LintError) as ei:
        PassManager([bad]).run(cj, PassContext())
    assert "GL301" in str(ei.value)
    assert aot.XLA_COMPILES.count == before


def test_quantize_int8_engine_parity_and_zero_recompiles():
    """The refactored int8 tier: the quantize pass over the shared AOT
    build path — parity within 2 % of output scale, argmax identical,
    int8 resident weights, receipts stamped, 0 post-warmup recompiles,
    and ``dtype="int8"`` sugar bitwise-equal to the explicit pass."""
    net = _mlp()
    x = np.random.RandomState(4).rand(6, *SAMPLE).astype(np.float32)
    fp = ServeEngine(net, buckets=(8,), lint="error")
    fp.warmup(np.zeros(SAMPLE, np.float32))
    ref = np.asarray(fp.infer(x))

    e8 = ServeEngine(net, buckets=(4, 8), passes=("quantize_int8",),
                     lint="error")
    e8.warmup(np.zeros(SAMPLE, np.float32))
    got = np.asarray(e8.infer(x))
    tol = 0.02 * np.abs(ref).max()
    np.testing.assert_allclose(got, ref, atol=tol)
    assert np.argmax(got, 1).tolist() == np.argmax(ref, 1).tolist()
    quant = [v for v, q in zip(e8._p_vals, e8._quantized) if q]
    assert quant and all(v[0].dtype == np.int8 for v in quant)
    # receipts: the 4x resident-weight story, per bucket program
    assert len(e8.pass_receipts) == 2
    for receipts in e8.pass_receipts.values():
        r = receipts[0]
        assert r.installed and r.name == "quantize_int8"
        assert r.param_bytes_after < 0.35 * r.param_bytes_before
    # steady state never compiles
    rs = np.random.RandomState(2)
    for n in (1, 4, 6, 8, 3):
        e8.infer(rs.rand(n, *SAMPLE).astype(np.float32))
    assert e8.recompile_count == 0
    # dtype sugar is THE pass (the engine-private branch is gone)
    sugar = ServeEngine(net, buckets=(4, 8), dtype="int8", lint="error")
    sugar.warmup(np.zeros(SAMPLE, np.float32))
    np.testing.assert_array_equal(np.asarray(sugar.infer(x)), got)
    # hot swap re-quantizes the candidate through the same transform
    v2 = e8.update_params([np.asarray(p._data._data) * 1.02
                           for p in e8._params])
    assert v2 == 2 and e8.recompile_count == 0


def test_quantize_int4_tier_for_free():
    net = _mlp()
    x = np.random.RandomState(5).rand(4, *SAMPLE).astype(np.float32)
    fp = ServeEngine(net, buckets=(4,), lint="error")
    fp.warmup(np.zeros(SAMPLE, np.float32))
    ref = np.asarray(fp.infer(x))
    e4 = ServeEngine(net, buckets=(4,), passes=("quantize_int4",),
                     lint="error")
    e4.warmup(np.zeros(SAMPLE, np.float32))
    got = np.asarray(e4.infer(x))
    np.testing.assert_allclose(got, ref, atol=0.4 * np.abs(ref).max())
    codes = [np.asarray(v[0]) for v, q in zip(e4._p_vals, e4._quantized)
             if q]
    assert codes and all(c.dtype == np.int8 for c in codes)
    assert all(c.min() >= -7 and c.max() <= 7 for c in codes)


def test_amp_pass_on_train_step():
    x, y = _batch()
    s0 = _dense_step(lint="off")
    l0 = [float(s0(x, y).asscalar()) for _ in range(2)]
    s1 = _dense_step(passes=("amp_bf16",), lint="error")
    l1 = [float(s1(x, y).asscalar()) for _ in range(2)]
    assert np.allclose(l0, l1, rtol=0.05)
    r = s1.pass_receipts[0]
    assert r.installed and r.hits >= 2 and r.contract.startswith("tol")


def test_train_step_cse_dp_mesh_golden_parity():
    """The dp-mesh leg: zero=1 + cse_dead_aux under lint="error" +
    cost="check" — losses match the un-rewritten step to float noise
    (the pass is bit_exact; only XLA scheduling may differ) and the
    receipts carry the bitwise probe verdict."""
    x, y = _batch()
    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    s0 = _dense_step(mesh=mesh, zero=1, lint="error", cost="check")
    l0 = [float(s0(x, y).asscalar()) for _ in range(3)]
    s1 = _dense_step(passes=("cse_dead_aux",), mesh=mesh, zero=1,
                     lint="error", cost="check")
    l1 = [float(s1(x, y).asscalar()) for _ in range(3)]
    np.testing.assert_allclose(l1, l0, rtol=1e-6)
    r = s1.pass_receipts[0]
    assert r.installed and r.probe["bitwise"] is True
    assert s1.cost_report is not None  # post-pass cost, GL201-gated


# ---------------------------------------------------------------------------
# the refusal gates
# ---------------------------------------------------------------------------

def test_gl301_contract_violation_refused_zero_compiles():
    """A deliberately wrong pass is refused at trace time: LintError
    naming GL301, no executable exists, no XLA compile was spent."""
    x, y = _batch()
    step = _dense_step(passes=(_ValueBreaker(),), lint="off")
    c0 = aot.XLA_COMPILES.count
    with pytest.raises(LintError, match="GL301"):
        step(x, y)
    assert step._compiled is None
    assert aot.XLA_COMPILES.count == c0
    # non-raising manager mode: the receipt says refused, not installed
    def f(a, b):
        return jnp.tanh(a @ b)

    cj = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((8, 8), jnp.float32),
                           jax.ShapeDtypeStruct((8, 8), jnp.float32))
    with pytest.warns(UserWarning, match="GL301"):
        res = PassManager([_ValueBreaker()],
                          raise_on_error=False).run(cj, PassContext())
    r = res.receipts[0]
    assert r.changed and not r.installed
    assert any(d.code == "GL301" for d in r.diagnostics)
    assert not res.changed  # the original program is what remains


def test_gl301_abstract_eval_interface_change_refused():
    class _Widens(GraftPass):
        name = "_test_widens"
        contract = Contract.bit_exact()

        def run(self, closed, ctx):
            jaxpr, consts = closed.jaxpr, closed.consts

            def wider(*args):
                outs = jax.core.eval_jaxpr(jaxpr, consts, *args)
                return [o.astype(jnp.float64) for o in outs]

            specs = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                     for v in jaxpr.invars]
            return PassResult(jax.make_jaxpr(wider)(*specs), hits=1)

    cj = jax.make_jaxpr(lambda a: a * 2.0)(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    with pytest.raises(LintError, match="GL301"):
        PassManager([_Widens()]).run(cj, PassContext())


def test_gl302_relint_gate_refuses_introduced_findings():
    """A rewrite that returns a donated invar as two outputs introduces
    a GL003 finding the input program did not have — the re-lint gate
    refuses it even though output avals match."""
    class _AliasesDonated(GraftPass):
        name = "_test_aliases_donated"
        contract = Contract.bit_exact()

        def run(self, closed, ctx):
            jaxpr = closed.jaxpr

            def evil(p, x):
                return p, p   # the donated invar, twice

            specs = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                     for v in jaxpr.invars]
            return PassResult(jax.make_jaxpr(evil)(*specs), hits=1)

    def f(p, x):
        return p - x, p * 1.0   # two outputs with p's aval

    cj = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((8,), jnp.float32),
                           jax.ShapeDtypeStruct((8,), jnp.float32))
    ctx = PassContext(donated_leaves=(0,), probe="off")
    with pytest.raises(LintError, match="GL302"):
        PassManager([_AliasesDonated()]).run(cj, ctx)


def test_invar_change_refused_where_layout_is_pinned():
    """The train step pins its invar layout (donation/shardings): a
    quantize pass must no-op there, and an invar-changing result is a
    hard error under allow_invar_change=False."""
    x, y = _batch()
    s = _dense_step(passes=("quantize_int8",), lint="off")
    loss = float(s(x, y).asscalar())
    assert np.isfinite(loss)
    assert not s.pass_receipts[0].changed  # no eligible param invars
    # manager-level: an invar-splitting result against a pinned layout
    def g(w, x2):
        return x2 @ w

    cj = jax.make_jaxpr(g)(jax.ShapeDtypeStruct((8, 4), jnp.float32),
                           jax.ShapeDtypeStruct((2, 8), jnp.float32))
    ctx = PassContext(param_invars=frozenset([0]),
                      allow_invar_change=False, probe="off")
    with pytest.raises(ValueError, match="invar layout"):
        PassManager(["quantize_int8"]).run(cj, ctx)


# ---------------------------------------------------------------------------
# autotune: passes as on/off knobs
# ---------------------------------------------------------------------------

def test_autotune_ranks_pass_knobs_and_rejects_gl301_at_zero_compiles():
    from incubator_mxnet_tpu.analysis.autotune import (autotune_train,
                                                       default_train_space)

    register_pass("_test_value_breaker", _ValueBreaker())
    try:
        base = default_train_space({}, batches=(8,))
        crossed = default_train_space({}, batches=(8,),
                                      passes=("cse_dead_aux",))
        assert len(crossed) == 2 * len(base)
        assert {c["passes"] for c in crossed} == {(), ("cse_dead_aux",)}
        space = [
            {"batch": 8, "passes": ()},
            {"batch": 8, "passes": ("cse_dead_aux",)},
            {"batch": 8, "passes": ("_test_value_breaker",)},
        ]
        c0 = aot.XLA_COMPILES.count
        # the broken candidate is the default so it reaches the measure
        # phase: ranking is probe-free (zero eager executions), and the
        # GL301 probe fires at build time — BEFORE its compile
        res = autotune_train(space=space, budget_compiles=2,
                             warmup=1, iters=1,
                             default_knobs=space[2])
        assert res.accounted()
        broken = [c for c in res.candidates
                  if c.knobs["passes"] == ("_test_value_breaker",)][0]
        assert broken.status in ("rejected-invalid", "measure-error")
        assert "GL301" in broken.reason
        assert broken.compiles_spent == 0    # refused pre-compile
        ranked = [c for c in res.candidates
                  if c.knobs["passes"] != ("_test_value_breaker",)]
        assert all(c.pred_sps is not None for c in ranked)
        assert res.compiles_spent == aot.XLA_COMPILES.count - c0 <= 2
    finally:
        PASS_REGISTRY.pop("_test_value_breaker", None)


# ---------------------------------------------------------------------------
# the CLI gate (tools/graftpass.py)
# ---------------------------------------------------------------------------

def test_cli_list_and_json_schema(capsys):
    import tools.graftpass as gp

    assert gp.main(["--list", "--format", "json"]) == 0
    reg = json.loads(capsys.readouterr().out)
    assert reg["tool"] == "graftpass"
    assert {r["name"] for r in reg["registry"]} >= {
        "quantize_int8", "quantize_int4", "amp_bf16", "space_to_depth",
        "cse_dead_aux"}
    rc = gp.main(["--model", "dense",
                  "--passes", "quantize_int8,cse_dead_aux",
                  "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["version"] == 1 and out["tool"] == "graftpass"
    assert out["summary"]["installed"] >= 1
    assert out["summary"]["errors"] == 0
    q = [p for p in out["passes"] if p["name"] == "quantize_int8"][0]
    assert q["installed"] and q["param_bytes_after"] \
        < q["param_bytes_before"]


def test_cli_exit_1_on_contract_violation(capsys):
    import tools.graftpass as gp

    register_pass("_test_cli_breaker", _ValueBreaker())
    try:
        with pytest.warns(UserWarning, match="GL301"):
            rc = gp.main(["--model", "dense",
                          "--passes", "_test_cli_breaker",
                          "--format", "json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["summary"]["errors"] >= 1
        assert any(d["code"] == "GL301" for d in out["diagnostics"])
    finally:
        PASS_REGISTRY.pop("_test_cli_breaker", None)
    assert gp.main(["--model", "dense", "--passes", "no_such_pass"]) == 1
