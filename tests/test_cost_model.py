"""graftcost: trace-time cost model (analysis/cost_model.py, GL2xx).

Golden-value tests hand-compute FLOPs/bytes/peak for programs small
enough to count on paper (matmul, fused elementwise chain, reduce
fusion, the BN stats/normalize two-pass pattern, donation aliasing),
then the step-level contracts: Dense-stack category totals, ZeRO-1
per-device state bytes exactly matching test_zero_sharding's measured
544 B / 4,352 B, GL201 rejecting an over-budget config at trace time
(no compile, no execution), production dp / dp x pp / zero=1 steps
running clean under ``cost="check"``, and the PERF.md accounting
regression: ResNet-50 batch-256 predicted HBM traffic within +-15 % of
the measured ~70 GiB/step.
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.analysis import (CODES, DEVICE_SPECS, LintError,
                                          LintReport, Severity,
                                          analyze_traceable, code_matches)
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import make_mesh, make_train_step

FEAT = 16


def _dense_net(widths=(FEAT,) * 4, seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    for w in widths:
        net.add(nn.Dense(w, activation="tanh"))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, FEAT)))
    return net


def _batch(batch=16):
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(batch, FEAT).astype(np.float32))
    y = nd.array((np.arange(batch) % 4).astype(np.float32))
    return x, y


# ---------------------------------------------------------------------------
# the catalog contract
# ---------------------------------------------------------------------------

def test_gl2xx_cataloged():
    assert CODES["GL201"][0] == Severity.ERROR
    for code in ("GL202", "GL203", "GL204"):
        assert CODES[code][0] == Severity.WARNING


def test_code_glob_matching_and_suppress():
    assert code_matches("GL201", "GL201")
    assert code_matches("GL201", "GL2*")
    assert code_matches("GL203", "GL?0[23]")
    assert not code_matches("GL101", "GL2*")
    from incubator_mxnet_tpu.analysis import Diagnostic

    rep = LintReport(suppress=("GL2*",))
    rep.add(Diagnostic("GL201", Severity.ERROR, "x"))
    rep.add(Diagnostic("GL101", Severity.ERROR, "y"))
    assert [d.code for d in rep] == ["GL101"]
    assert [d.code for d in rep.suppressed] == ["GL201"]


# ---------------------------------------------------------------------------
# golden values: paper-countable programs
# ---------------------------------------------------------------------------

def test_golden_matmul_flops_and_bytes():
    """One dot: 2·M·K·N FLOPs; reads both operands, writes the out."""
    a = jnp.zeros((64, 128), jnp.float32)
    b = jnp.zeros((128, 32), jnp.float32)
    r = analyze_traceable(lambda a, b: a @ b, (a, b))
    conv = r.categories["conv"]
    assert conv.flops == 2 * 64 * 128 * 32
    assert conv.hbm_read_bytes == 64 * 128 * 4 + 128 * 32 * 4
    assert conv.hbm_write_bytes == 64 * 32 * 4
    # peak: both inputs live (non-donated: held to program end) + out
    assert r.peak_bytes == 64 * 128 * 4 + 128 * 32 * 4 + 64 * 32 * 4


def test_golden_elementwise_chain_fuses_to_one_pass():
    """tanh(x·2+1): one fused pass — read x once, write the result,
    3 FLOPs/element; the mul/add intermediates never touch HBM."""
    x = jnp.zeros((256, 1024), jnp.float32)
    r = analyze_traceable(lambda x: jnp.tanh(x * 2.0 + 1.0), (x,))
    elem = r.categories["elementwise"]
    n, b = 256 * 1024, 256 * 1024 * 4
    assert elem.passes == 1
    assert elem.flops == 3 * n
    assert elem.hbm_read_bytes == b
    assert elem.hbm_write_bytes == b
    assert "reduction" not in r.categories
    assert "conv" not in r.categories


def test_golden_reduce_fusion_reads_operand_once():
    """sum(x·x): the square fuses INTO the reduction
    (convert_reduce_fusion) — one read of x, a scalar write."""
    x = jnp.zeros((512, 512), jnp.float32)
    r = analyze_traceable(lambda x: jnp.sum(x * x), (x,))
    red = r.categories["reduction"]
    assert red.hbm_read_bytes == 512 * 512 * 4
    assert red.hbm_write_bytes == 4
    assert red.flops == 512 * 512          # the reduce
    assert r.categories["elementwise"].flops == 512 * 512  # the square


def test_golden_bn_pattern_two_passes_and_gl202():
    """stats + normalize = TWO passes over x (PERF.md's measured BN
    behavior): the reduce pass reads x once (mean and mean-of-squares
    co-fuse), the normalize pass reads it again."""
    x = jnp.zeros((1 << 22,), jnp.float32)  # 16 MB: over the GL202 bar

    def bn_ish(x):
        mean = jnp.mean(x)
        var = jnp.mean(x * x) - mean * mean
        return (x - mean) * jax.lax.rsqrt(var + 1e-5)

    r = analyze_traceable(bn_ish, (x,))
    b = (1 << 22) * 4
    assert r.categories["reduction"].hbm_read_bytes == b      # one pass
    # one more pass over x, plus the two materialized scalar stats
    assert r.categories["elementwise"].hbm_read_bytes == b + 8
    assert r.categories["elementwise"].hbm_write_bytes == b
    gl202 = [d for d in r.diagnostics if d.code == "GL202"]
    assert len(gl202) == 1
    assert "re-read" in gl202[0].message


def test_golden_donation_aliases_matching_output():
    """p - 0.1·g with p donated: the output reuses p's buffer, so peak
    is p+g — without donation a third buffer appears."""
    p = jnp.zeros((1024, 1024), jnp.float32)
    g = jnp.zeros((1024, 1024), jnp.float32)
    b = 1024 * 1024 * 4
    fn = lambda p, g: p - 0.1 * g  # noqa: E731
    r_don = analyze_traceable(fn, (p, g), donate_argnums=(0,))
    r_not = analyze_traceable(fn, (p, g))
    assert r_don.peak_bytes == 2 * b
    assert r_not.peak_bytes == 3 * b
    # traffic is identical — donation is a memory knob, not a bytes knob
    assert r_don.hbm_bytes == r_not.hbm_bytes


# ---------------------------------------------------------------------------
# step-level: Dense stack (fwd+bwd+update)
# ---------------------------------------------------------------------------

def test_dense_stack_step_costs():
    """4 x Dense(16) fused step at batch 16: 11 matmuls (4 fwd, 3 dX —
    the first layer needs no input grad — 4 dW), hand-counted MXU
    FLOPs; state bytes = the momentum tree exactly."""
    step = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                           optimizer="sgd", learning_rate=0.1, momentum=0.9,
                           lint="off")
    x, y = _batch()
    r = step.analyze_cost(x, y)
    assert r.categories["conv"].passes == 11
    assert r.categories["conv"].flops == 11 * 2 * 16 * 16 * 16
    # sgd-momentum state: one f32 buffer per param
    assert r.opt_state_bytes == 4 * (16 * 16 + 16) * 4 == 4352
    assert r.opt_state_bytes_per_device == 4352
    assert r.param_bytes == 4352
    rf = r.roofline()
    assert rf["step_s"] >= max(rf["compute_s"], rf["hbm_s"])
    # serialization round-trip keeps the schema
    d = json.loads(r.to_json())
    assert d["version"] == 1
    assert set(d["totals"]) == {"flops", "hbm_read_bytes",
                                "hbm_write_bytes", "hbm_bytes"}
    assert "conv" in d["categories"] and "roofline" in d


def test_dense_stack_donation_off_raises_peak_and_gl204():
    x, y = _batch()
    s_don = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            optimizer="sgd", learning_rate=0.1, momentum=0.9,
                            lint="off")
    s_not = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            optimizer="sgd", learning_rate=0.1, momentum=0.9,
                            donate=False, lint="off")
    r_don = s_don.analyze_cost(x, y)
    r_not = s_not.analyze_cost(x, y)
    assert r_not.peak_bytes > r_don.peak_bytes
    assert any(d.code == "GL204" for d in r_not.diagnostics)
    assert not any(d.code == "GL204" for d in r_don.diagnostics)


def test_zero1_state_bytes_exactly_reproduce_measured_figures():
    """The cost model PREDICTS (at trace time, from shardings alone)
    the per-device ZeRO-1 state bytes tests/test_zero_sharding.py
    MEASURES via .addressable_shards: 4,352 B total, 544 B/device at
    dp=8 for the sgd-momentum Dense stack; adam doubles both."""
    x, y = _batch()
    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    s = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                        optimizer="sgd", learning_rate=0.1, momentum=0.9,
                        mesh=mesh, zero=1, lint="off")
    r = s.analyze_cost(x, y)
    assert r.opt_state_bytes == 4352
    assert r.opt_state_bytes_per_device == 544
    s_adam = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                             optimizer="adam", learning_rate=0.01,
                             mesh=mesh, zero=1, lint="off")
    r_adam = s_adam.analyze_cost(x, y)
    assert r_adam.opt_state_bytes == 8704
    assert r_adam.opt_state_bytes_per_device == 1088
    # the replicated step keeps the full copy per device
    s_rep = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            optimizer="sgd", learning_rate=0.1, momentum=0.9,
                            mesh=mesh, lint="off")
    r_rep = s_rep.analyze_cost(x, y)
    assert r_rep.opt_state_bytes_per_device == 4352
    # ZeRO's explicit all-gather shows up as dp comm (params re-
    # materialize: (n-1)/n of the padded param bytes per device)
    assert "dp" in r.comm
    assert r.comm["dp"].payload_bytes == 4352
    assert r.comm["dp"].wire_bytes == pytest.approx(4352 * 7 / 8)


# ---------------------------------------------------------------------------
# GL201: the eager infeasibility gate
# ---------------------------------------------------------------------------

def test_gl201_rejects_over_budget_at_trace_time():
    """cost="check" with a tiny hbm_budget raises BEFORE any compile:
    no executable exists and no step ran."""
    step = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                           optimizer="sgd", learning_rate=0.1, momentum=0.9,
                           lint="off", cost="check", hbm_budget=1024)
    x, y = _batch()
    with pytest.raises(LintError) as ei:
        step(x, y)
    assert "GL201" in str(ei.value)
    assert step._compiled is None
    assert step._step_count == 0
    # the report is still inspectable for debugging
    assert step.cost_report is not None
    assert step.cost_report.peak_bytes > 1024
    # lint_suppress accepts the GL2* glob and un-gates it
    step2 = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            optimizer="sgd", learning_rate=0.1, momentum=0.9,
                            lint="off", cost="check", hbm_budget=1024,
                            lint_suppress=("GL2*",))
    loss = step2(x, y)
    assert np.isfinite(float(loss.asscalar()))


def test_cost_check_clean_on_production_steps():
    """dp, dp x pp (pipelined) and zero=1 steps run clean under
    cost="check" with a realistic budget — the acceptance gate for the
    dryrun legs."""
    x, y = _batch()
    budget = DEVICE_SPECS["tpu-v5e"].hbm_bytes
    mesh_dp = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    mesh_pp = make_mesh({"dp": 2, "pp": 4}, devices=jax.devices()[:8])
    losses = []
    for kw in (dict(mesh=mesh_dp),
               dict(mesh=mesh_pp, pipeline_stages=4, num_micro=4),
               dict(mesh=mesh_dp, zero=1)):
        s = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            optimizer="sgd", learning_rate=0.1, momentum=0.9,
                            lint="error", cost="check", hbm_budget=budget,
                            **kw)
        losses.append(float(s(x, y).asscalar()))
        assert s.cost_report is not None
        assert not [d for d in s.cost_report.diagnostics
                    if d.severity >= Severity.ERROR]
    assert np.allclose(losses, losses[0], rtol=1e-5)


def test_pipeline_remat_adds_traffic():
    """pipeline_remat=True recomputes stage activations: the cost model
    sees the extra bytes in the traced program itself, and GL204 flags
    paying them when peak sits far under budget."""
    x, y = _batch()
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    kw = dict(optimizer="sgd", learning_rate=0.1, momentum=0.9,
              pipeline_stages=4, num_micro=4, lint="off")
    s_plain = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                              mesh=mesh, **kw)
    s_remat = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                              mesh=mesh, pipeline_remat=True, **kw)
    r_plain = s_plain.analyze_cost(x, y)
    r_remat = s_remat.analyze_cost(x, y)
    assert r_remat.hbm_bytes >= r_plain.hbm_bytes
    assert any(d.code == "GL204" for d in r_remat.diagnostics)


def test_gl203_comm_dominated():
    """A synthetic report whose collective wire time dwarfs both
    rooflines draws the comm-dominated warning."""
    from incubator_mxnet_tpu.analysis.cost_model import (CategoryCost,
                                                         CommCost,
                                                         CostReport,
                                                         check_cost)

    rep = CostReport(device="tpu-v5e", n_devices=8)
    rep.categories["conv"] = CategoryCost(flops=1e9, hbm_read_bytes=1e6,
                                          hbm_write_bytes=1e6, passes=1)
    rep.comm["dp"] = CommCost(payload_bytes=1e12, wire_bytes=1e12, ops=1)
    diags = check_cost(rep)
    assert any(d.code == "GL203" for d in diags)
    assert not any(d.code == "GL201" for d in diags)  # no budget set


def test_env_var_resolution(monkeypatch):
    monkeypatch.setenv("MXTPU_COST", "report")
    s = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                        optimizer="sgd", learning_rate=0.1, lint="off")
    assert s.cost == "report"
    monkeypatch.delenv("MXTPU_COST")
    s2 = make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd", learning_rate=0.1, lint="off")
    assert s2.cost == "off"
    with pytest.raises(ValueError, match="cost must be"):
        make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                        optimizer="sgd", learning_rate=0.1, cost="loud")
    with pytest.raises(ValueError, match="hbm_budget"):
        make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                        optimizer="sgd", learning_rate=0.1, cost="check",
                        hbm_budget=-1)
    with pytest.raises(ValueError, match="cost_device"):
        make_train_step(_dense_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                        optimizer="sgd", learning_rate=0.1,
                        cost_device="tpu-v9000")


def test_trainer_make_fused_step_passes_cost_through():
    net = _dense_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = trainer.make_fused_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                   lint="off", cost="check", hbm_budget=1024)
    assert step.cost == "check" and step.hbm_budget == 1024
    x, y = _batch()
    with pytest.raises(LintError, match="GL201"):
        step(x, y)


# ---------------------------------------------------------------------------
# PERF.md accounting regression (the acceptance anchor)
# ---------------------------------------------------------------------------

def test_resnet50_batch256_bytes_within_15pct_of_perf_md():
    """docs/PERF.md round-3 measurement: the fused ResNet-50 step at
    batch 256 moves ~70 GiB/step (~280 MB/img, 100 ms busy at ~680
    GiB/s).  The fusion-aware model must land within +-15 % — the
    regression that keeps graftcost anchored to reality instead of
    drifting with walker refactors."""
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Zero())   # Zero: no RNG cost, same shapes
    net.shape_init((1, 3, 224, 224))
    step = make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                           optimizer="sgd", learning_rate=0.1, momentum=0.9,
                           wd=1e-4, compute_dtype="bfloat16", lint="off")
    B = 256
    r = step.analyze_cost(jax.ShapeDtypeStruct((B, 3, 224, 224), jnp.float32),
                          jax.ShapeDtypeStruct((B,), jnp.float32))
    gib = r.hbm_bytes / 2**30
    assert 70 * 0.85 <= gib <= 70 * 1.15, \
        "predicted %.1f GiB/step vs measured ~70 GiB (docs/PERF.md)" % gib
    # per-image sanity against the 280 MB/img table row
    mb_img = r.hbm_bytes / B / 1e6
    assert 230 <= mb_img <= 340, mb_img
    # the BN multi-pass pattern is what GL202 exists to flag
    assert any(d.code == "GL202" for d in r.diagnostics)
    # compute is nowhere near the bound — the step is memory-bound, as
    # measured (13.9 % MFU)
    rf = r.roofline()
    assert rf["hbm_s"] > rf["compute_s"]
    # peak fits the 16 GiB device: the config is feasible, as reality
    # agrees it is
    assert r.peak_bytes < DEVICE_SPECS["tpu-v5e"].hbm_bytes
