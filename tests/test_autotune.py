"""Search-based autotuner + persistent compile cache (ROADMAP item 2).

Contracts under test (analysis/autotune.py, parallel/aot.py):

- candidate RANKING is pure graftcost: the tuner's predicted
  seconds-per-sample equal an independent ``analyze_cost`` of the same
  config (golden agreement, Dense model);
- GL201-infeasible candidates are pruned EAGERLY: zero XLA compiles
  spent, the built step's ``_compiled is None``, the rejection reason
  names GL201;
- measured refinement touches exactly ``budget_compiles`` candidates
  and the JSON tuning log accounts for 100 % of the space;
- the learned residual strictly improves rank correlation on a
  synthetic drift set whose roofline ranking is wrong;
- a warm persistent compile cache makes an identical (lowered program,
  mesh, knobs) build perform 0 XLA compiles — in-process AND from a
  fresh subprocess — with bit-identical results;
- a torn/corrupt/garbage cache entry degrades to recompile-with-warning
  (never a crash, never a wrong executable), and a failed store
  (``fault_injection.fail_writes`` riding the CheckpointManager
  byte-writer) leaves the step working uncached.

Measured-refinement soaks beyond the minimal contract are marked
``slow`` — tier-1 is at its 870 s budget ceiling.
"""
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.analysis import (autotune_serve, autotune_train,
                                          fit_residual, spearman)
from incubator_mxnet_tpu.analysis.autotune import (apply_residual,
                                                   backend_status,
                                                   default_serve_space,
                                                   default_train_space,
                                                   dense_workload)
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import aot, make_train_step
from incubator_mxnet_tpu.parallel import fault_injection as fi
from incubator_mxnet_tpu.parallel.distributed import collectives_supported

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: a budget between the Dense workload's batch-8 peak (~15.5 KB) and its
#: batch-32 peak (~24.6 KB): splits the space into feasible + GL201
SPLIT_BUDGET = 20_000


def _dense_step(batch=8, optimizer="sgd", **kw):
    mk, mb, loss_fn = dense_workload()
    knobs = {"batch": batch}
    net = mk(knobs)
    if optimizer == "sgd":
        kw.setdefault("momentum", 0.9)
    step = make_train_step(net, loss_fn, optimizer=optimizer,
                           learning_rate=0.1, lint="off",
                           cost="off", **kw)
    x, y = mb(knobs)
    return step, x, y


# ---------------------------------------------------------------------------
# ranking + pruning + accounting
# ---------------------------------------------------------------------------

def test_ranking_matches_graftcost_golden():
    """The tuner's predicted score is exactly graftcost's roofline
    step-time over the batch — computed independently per config."""
    space = [{"batch": b, "zero": 0, "multi_precision": False,
              "loss_scale": None, "pipeline_stages": None,
              "num_micro": 1, "pipeline_remat": False}
             for b in (8, 16, 32)]
    res = autotune_train(space=space, device="cpu-proxy",
                         budget_compiles=0)
    assert [c.status for c in res.candidates] == ["predicted"] * 3
    for c in res.candidates:
        step, x, y = _dense_step(batch=c.knobs["batch"])
        rep = step.analyze_cost(x, y, device="cpu-proxy")
        golden = rep.roofline()["step_s"] / c.knobs["batch"]
        assert c.pred_sps == pytest.approx(golden, rel=1e-9), c.knobs
    # and the ranking follows: bigger batch amortizes better per sample
    scores = [c.pred_sps for c in res.candidates]
    assert scores == sorted(scores, reverse=True)


def test_conv_bn_ranking_matches_golden():
    """Same golden agreement on the conv-bn model (the second
    graftcost test net) through the CLI's workload builder."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from autotune import _conv_bn_workload
    finally:
        sys.path.pop(0)
    mk, mb, loss_fn = _conv_bn_workload()
    space = [{"batch": b, "zero": 0, "multi_precision": False,
              "loss_scale": None, "pipeline_stages": None,
              "num_micro": 1, "pipeline_remat": False} for b in (4, 8)]
    res = autotune_train(mk, mb, loss_fn, space=space, device="cpu-proxy",
                         budget_compiles=0)
    from incubator_mxnet_tpu.parallel import make_train_step as mts

    for c in res.candidates:
        assert c.status == "predicted"
        net = mk(c.knobs)
        step = mts(net, loss_fn, optimizer="sgd", learning_rate=0.1,
                   momentum=0.9, lint="off", cost="off")
        x, y = mb(c.knobs)
        rep = step.analyze_cost(x, y, device="cpu-proxy")
        golden = rep.roofline()["step_s"] / c.knobs["batch"]
        assert c.pred_sps == pytest.approx(golden, rel=1e-9)


def test_gl201_pruned_with_zero_compiles():
    """Infeasible candidates are rejected at trace time: no compile is
    ever paid for them, and the step they were costed on never owned an
    executable (``_compiled is None``)."""
    c0 = aot.XLA_COMPILES.count
    res = autotune_train(device="cpu-proxy", hbm_budget=SPLIT_BUDGET,
                         budget_compiles=0)
    rejected = [c for c in res.candidates
                if c.status == "rejected-infeasible"]
    feasible = [c for c in res.candidates if c.status == "predicted"]
    assert rejected and feasible, \
        [c.status for c in res.candidates]  # the budget splits the space
    assert aot.XLA_COMPILES.count == c0  # ZERO compiles spent
    for c in rejected:
        assert c.zero_compile is True
        assert "GL201" in c.reason
        assert c.pred["peak_bytes"] > SPLIT_BUDGET
    # the direct form: an over-budget step is rejected pre-compile
    step, x, y = _dense_step(batch=32)
    rep = step.analyze_cost(x, y, device="cpu-proxy",
                            hbm_budget=SPLIT_BUDGET)
    assert any(d.code == "GL201" for d in rep.diagnostics)
    assert step._compiled is None
    assert aot.XLA_COMPILES.count == c0


def test_measured_refinement_budget_and_log_accounting(tmp_path):
    """budget_compiles bounds the measured set; every candidate lands
    in the JSON log with a prediction and a measurement-or-reason."""
    log = str(tmp_path / "tuning.json")
    c0 = aot.XLA_COMPILES.count
    res = autotune_train(device="cpu-proxy", hbm_budget=SPLIT_BUDGET,
                         budget_compiles=2, warmup=1, iters=1,
                         log_path=log)
    measured = [c for c in res.candidates if c.status == "measured"]
    assert len(measured) == 2
    assert res.compiles_spent == aot.XLA_COMPILES.count - c0 <= 2
    assert res.accounted()
    assert res.winner is not None and res.winner in measured
    assert res.winner.measured_sps == min(c.measured_sps for c in measured)
    d = json.loads(open(log).read())
    assert d["accounted"] is True
    assert d["space_size"] == len(res.candidates)
    statuses = {c["status"] for c in d["candidates"]}
    assert "pending" not in statuses
    for c in d["candidates"]:
        if c["status"].startswith("rejected"):
            assert c["reason"]
        if c["status"] == "measured":
            assert c["measured_s_per_sample"] is not None
    # the never-silence stamp: off-TPU results say so explicitly
    backend, unavailable = backend_status()
    assert d["backend"] == backend
    assert d["tpu_unavailable"] is unavailable is True  # CPU suite
    assert d["relative_only"] is True


# ---------------------------------------------------------------------------
# the learned residual
# ---------------------------------------------------------------------------

def test_residual_improves_rank_correlation_on_synthetic_drift():
    """A drift set whose true cost model (measured = 0.2*compute +
    3*hbm + const) disagrees with the max() roofline ranking: the
    fitted per-category correction must strictly improve Spearman."""
    rng = np.random.RandomState(7)
    preds, measured, naive = [], [], []
    # compute-heavy candidates look slow to the roofline but are cheap
    # in truth; hbm-heavy ones the reverse
    for compute_ms, hbm_ms in [(10, 1), (8, 2), (6, 3), (1, 8), (2, 7),
                               (3, 6), (5, 4), (4, 5)]:
        p = {"compute_s": compute_ms / 1e3, "hbm_s": hbm_ms / 1e3,
             "comm_s": 0.0}
        preds.append(p)
        naive.append(max(p["compute_s"], p["hbm_s"]))
        measured.append(0.2 * p["compute_s"] + 3.0 * p["hbm_s"] + 1e-3
                        + rng.uniform(0, 1e-5))
    beta = fit_residual(preds, measured)
    assert beta is not None
    corrected = [apply_residual(beta, p) for p in preds]
    s_naive = spearman(naive, measured)
    s_corr = spearman(corrected, measured)
    assert s_corr > s_naive, (s_naive, s_corr)
    assert s_corr > 0.95


def test_residual_underdetermined_returns_none():
    assert fit_residual([{"compute_s": 1.0, "hbm_s": 1.0, "comm_s": 0.0}],
                        [1.0]) is None
    assert apply_residual(None, {"compute_s": 1.0}) is None


def test_spearman_basics():
    assert spearman([1, 2, 3], [10, 20, 30]) == pytest.approx(1.0)
    assert spearman([1, 2, 3], [30, 20, 10]) == pytest.approx(-1.0)
    assert spearman([1.0, 1.0, 1.0], [1, 2, 3]) == 0.0  # degenerate
    assert spearman([1], [1]) == 0.0


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------

def test_warm_cache_zero_compiles_in_process(tmp_path):
    """Second build of an identical (lowered program, mesh, knobs) key:
    0 XLA compiles, bit-identical results."""
    cache = aot.CompileCache(str(tmp_path))
    step, x, y = _dense_step()
    t1 = step.aot_compile(x, y, cache=cache)
    assert t1["cache"] == "stored"
    loss_ref = float(step(x, y).asscalar())

    step2, x2, y2 = _dense_step()
    c0 = aot.XLA_COMPILES.count
    t2 = step2.aot_compile(x2, y2, cache=cache)
    assert t2["cache"] == "hit"
    assert t2["compile"] == 0.0
    assert aot.XLA_COMPILES.count == c0  # 0 XLA compiles
    assert float(step2(x2, y2).asscalar()) == loss_ref  # bit-identical
    assert cache.hits == 1


def test_warm_cache_zero_compiles_cross_process(tmp_path):
    """A fresh PROCESS rebuilding the same key performs 0 XLA compiles
    and returns bit-identical results (the restart/retune contract)."""
    if not collectives_supported():
        pytest.skip("backend cannot run the subprocess leg")
    cache = aot.CompileCache(str(tmp_path))
    step, x, y = _dense_step()
    assert step.aot_compile(x, y, cache=cache)["cache"] == "stored"
    loss_ref = float(step(x, y).asscalar())

    child = subprocess.run(
        [sys.executable, "-c", """
import sys, json
sys.path.insert(0, %r)
from _platform_pin import pin_cpu
jax = pin_cpu(8)
# conftest.py sets this in the parent; the lowered text (and so the
# cache key) depends on it
jax.config.update("jax_default_matmul_precision", "highest")
from tests.test_autotune import _dense_step
from incubator_mxnet_tpu.parallel import aot
step, x, y = _dense_step()
t = step.aot_compile(x, y)
print(json.dumps({"cache": t["cache"], "compiles": aot.XLA_COMPILES.count,
                  "loss": float(step(x, y).asscalar())}))
""" % REPO],
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 MXTPU_COMPILE_CACHE=str(tmp_path)),
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert child.returncode == 0, child.stderr[-2000:]
    rec = json.loads(child.stdout.strip().splitlines()[-1])
    assert rec["cache"] == "hit"
    assert rec["compiles"] == 0  # ZERO XLA compiles in the new process
    assert rec["loss"] == loss_ref  # bit-identical across processes


def test_corrupt_cache_entry_recompiles_with_warning(tmp_path):
    """Torn, bit-flipped and garbage entries: recompile-with-warning,
    bit-identical results, never a crash, never a wrong executable."""
    cache = aot.CompileCache(str(tmp_path))
    step, x, y = _dense_step()
    step.aot_compile(x, y, cache=cache)
    loss_ref = float(step(x, y).asscalar())
    for what in ("truncate", "garbage"):
        fi.corrupt_compile_cache(tmp_path, what=what)
        step2, x2, y2 = _dense_step()
        c0 = aot.XLA_COMPILES.count
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t = step2.aot_compile(x2, y2, cache=cache)
        assert any("corrupt or stale" in str(x.message) for x in w), \
            (what, [str(x.message) for x in w])
        assert aot.XLA_COMPILES.count == c0 + 1  # really recompiled
        assert t["cache"] == "stored"  # the bad entry was replaced
        assert float(step2(x2, y2).asscalar()) == loss_ref


def test_cache_store_failure_degrades_to_uncached(tmp_path):
    """fail_writes through the CheckpointManager byte-writer: the store
    fails loudly-but-harmlessly; the freshly-compiled step still runs."""
    cache = aot.CompileCache(str(tmp_path))
    step, x, y = _dense_step()
    with fi.fail_writes(at=0, count=10):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            t = step.aot_compile(x, y, cache=cache)
    assert t["cache"] == "store-failed"
    assert any("failed to store" in str(x.message) for x in w)
    assert np.isfinite(float(step(x, y).asscalar()))
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".xc")]


def test_cache_lru_sweep_is_size_capped(tmp_path):
    """Entries past max_bytes are LRU-swept (oldest mtime first)."""
    cache = aot.CompileCache(str(tmp_path))  # generous: both fit
    step, x, y = _dense_step()
    step.aot_compile(x, y, cache=cache)
    entry = [n for n in os.listdir(tmp_path) if n.endswith(".xc")]
    assert len(entry) == 1
    size = os.path.getsize(tmp_path / entry[0])
    # re-cap under one entry and store a DIFFERENT program (adam)
    os.utime(tmp_path / entry[0], (1, 1))  # make the first entry oldest
    cache.max_bytes = size
    step2, x2, y2 = _dense_step(optimizer="adam")
    step2.aot_compile(x2, y2, cache=cache)
    left = [n for n in os.listdir(tmp_path) if n.endswith(".xc")]
    assert entry[0] not in left  # the old entry was evicted
    total = sum(os.path.getsize(tmp_path / n) for n in left)
    assert total <= size


def test_cache_key_separates_knobs(tmp_path):
    """Different knob sets never collide: sgd and adam steps of the
    same net produce distinct entries."""
    cache = aot.CompileCache(str(tmp_path))
    step, x, y = _dense_step()
    step.aot_compile(x, y, cache=cache)
    step2, x2, y2 = _dense_step(optimizer="adam")
    t = step2.aot_compile(x2, y2, cache=cache)
    assert t["cache"] == "stored"  # not a (wrong) hit
    assert len([n for n in os.listdir(tmp_path)
                if n.endswith(".xc")]) == 2


def test_multi_precision_f32_master_weights_distinct_buffer():
    """Regression: multi_precision with f32 params used to alias the
    master weight onto the param buffer (astype no-op), making every
    donated step fail at execute with 'donate the same buffer twice'."""
    step, x, y = _dense_step(multi_precision=True)
    step.aot_compile(x, y)
    loss = step(x, y)  # raised XlaRuntimeError before the fix
    assert np.isfinite(float(loss.asscalar()))


def test_loadtest_objective_penalizes_failures():
    from incubator_mxnet_tpu.serve.loadtest import LoadReport

    clean = LoadReport(p99_ms=50.0)
    assert clean.objective() == pytest.approx(0.05)
    dirty = LoadReport(p99_ms=50.0, errors=1, expired=1, shed=2)
    assert dirty.objective() == pytest.approx(0.05 + 2.0 + 0.2)
    assert dirty.objective() > clean.objective()


def test_default_spaces_shape():
    assert len(default_train_space({"dp": 8})) == 24
    assert len(default_train_space({})) == 12  # no dp => no zero knobs
    pp = default_train_space({"dp": 2, "pp": 4})
    assert any(c["pipeline_stages"] == 4 for c in pp)
    assert all(len(set(c["buckets"])) == len(c["buckets"])
               for c in default_serve_space())


# ---------------------------------------------------------------------------
# slow soaks (tier-1 is at its budget ceiling)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_autotune_winner_beats_default_on_dp_mesh():
    """The acceptance sweep: ≥24 candidates on the 8-dev dp mesh,
    GL201 pruning, top-K measurement, winner beats the default."""
    from incubator_mxnet_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    res = autotune_train(mesh=mesh, device="cpu-proxy",
                         budget_compiles=5, warmup=1, iters=2)
    assert len(res.candidates) >= 24
    assert res.accounted()
    assert res.winner is not None
    assert res.winner.measured_sps <= res.default.measured_sps


@pytest.mark.slow
def test_autotune_serve_policy_search():
    """Serve target: bucket-set + flush-deadline policies ranked by the
    zero-compile latency proxy, top-K measured against the Poisson
    loadtest, every policy accounted."""
    mx.random.seed(8)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, 16)))
    res = autotune_serve(net, (16,), budget_compiles=2, qps=400.0,
                         n_requests=40)
    assert res.accounted()
    assert res.winner is not None
    assert res.winner.detail["recompiles"] == 0
    measured = [c for c in res.candidates if c.status == "measured"]
    assert len(measured) == 2
    assert res.winner.measured_sps == min(c.measured_sps for c in measured)
