"""Legacy mx.rnn cell API tests (model: tests/python/unittest/test_rnn.py
in the reference)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _bind_forward(outputs, data_shapes, seed=0):
    sym = outputs if isinstance(outputs, mx.Symbol) else mx.sym.Group(outputs)
    arg_shapes, _, _ = sym.infer_shape(**data_shapes)
    rng = np.random.RandomState(seed)
    args = {}
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        args[name] = mx.nd.array(rng.uniform(-0.5, 0.5, shape))
    exe = sym.bind(mx.current_context(), args)
    return exe.forward(is_train=False), args


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(10, prefix="rnn_")
    inputs = [mx.sym.Variable("t%d_data" % i) for i in range(3)]
    outputs, states = cell.unroll(3, inputs)
    outputs = mx.sym.Group(outputs)
    assert sorted(cell.params._params.keys()) == sorted(
        ["rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias", "rnn_i2h_weight"])
    _, out_shapes, _ = outputs.infer_shape(
        t0_data=(2, 20), t1_data=(2, 20), t2_data=(2, 20))
    assert [tuple(s) for s in out_shapes] == [(2, 10)] * 3


def test_lstm_cell_unroll_vs_numpy():
    T, N, C, H = 4, 3, 5, 6
    cell = mx.rnn.LSTMCell(H, prefix="lstm_", forget_bias=0.7)
    data = mx.sym.Variable("data")
    out, states = cell.unroll(T, data, layout="NTC", merge_outputs=True)
    vals, args = _bind_forward(out, {"data": (N, T, C)})
    res = vals[0].asnumpy()
    assert res.shape == (N, T, H)

    # numpy oracle
    x = args["data"].asnumpy()
    wi = args["lstm_i2h_weight"].asnumpy()
    bi = args["lstm_i2h_bias"].asnumpy()
    wh = args["lstm_h2h_weight"].asnumpy()
    bh = args["lstm_h2h_bias"].asnumpy()

    def sig(v):
        return 1 / (1 + np.exp(-v))

    h = np.zeros((N, H))
    c = np.zeros((N, H))
    for t in range(T):
        g = x[:, t] @ wi.T + bi + h @ wh.T + bh
        i, f, cc, o = np.split(g, 4, axis=1)
        i, f, o = sig(i), sig(f), sig(o)
        c = f * c + i * np.tanh(cc)
        h = o * np.tanh(c)
        np.testing.assert_allclose(res[:, t], h, rtol=2e-5, atol=2e-5)


def test_gru_cell_runs():
    cell = mx.rnn.GRUCell(8, prefix="gru_")
    data = mx.sym.Variable("data")
    out, _ = cell.unroll(3, data, layout="NTC", merge_outputs=True)
    vals, _ = _bind_forward(out, {"data": (2, 3, 4)})
    assert vals[0].shape == (2, 3, 8)


def test_stacked_and_bidirectional():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.BidirectionalCell(
        mx.rnn.GRUCell(4, prefix="bl_"), mx.rnn.GRUCell(4, prefix="br_")))
    data = mx.sym.Variable("data")
    out, states = stack.unroll(3, data, layout="NTC", merge_outputs=True)
    vals, _ = _bind_forward(out, {"data": (2, 3, 6)})
    assert vals[0].shape == (2, 3, 8)  # 4+4 bidirectional concat


def test_residual_and_dropout_cells():
    cell = mx.rnn.ResidualCell(mx.rnn.RNNCell(6, prefix="res_"))
    data = mx.sym.Variable("data")
    out, _ = cell.unroll(2, data, layout="NTC", merge_outputs=True)
    vals, _ = _bind_forward(out, {"data": (3, 2, 6)})
    assert vals[0].shape == (3, 2, 6)

    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.GRUCell(5, prefix="g0_"))
    stack.add(mx.rnn.DropoutCell(0.5))
    out, _ = stack.unroll(2, mx.sym.Variable("data"), merge_outputs=True)
    vals, _ = _bind_forward(out, {"data": (3, 2, 5)})
    assert vals[0].shape == (3, 2, 5)


def test_fused_rnn_cell_vs_unfused():
    """FusedRNNCell (lax.scan path) matches its unfuse() expansion given
    shared weights, like the reference's fused-vs-unfused consistency
    tests."""
    T, N, C, H, L = 5, 2, 4, 3, 2
    fused = mx.rnn.FusedRNNCell(H, num_layers=L, mode="lstm",
                                prefix="lstm_", get_next_state=True)
    data = mx.sym.Variable("data")
    f_out, f_states = fused.unroll(T, data, layout="NTC",
                                   merge_outputs=True)
    vals, args = _bind_forward(f_out, {"data": (N, T, C)})
    f_res = vals[0].asnumpy()
    assert f_res.shape == (N, T, H)

    unfused = fused.unfuse()
    u_out, _ = unfused.unroll(T, data, layout="NTC", merge_outputs=True)
    # map packed params onto unfused cell weights (forget_bias=0 for exact
    # match: fused adds forget_bias at init time not run time)
    unpacked = fused.unpack_weights({k: v for k, v in args.items()
                                     if k != "data"})
    u_sym = u_out
    arg_shapes, _, _ = u_sym.infer_shape(data=(N, T, C))
    feed = {"data": args["data"]}
    for name in u_sym.list_arguments():
        if name == "data":
            continue
        feed[name] = unpacked[name]
    exe = u_sym.bind(mx.current_context(), feed)
    u_res = exe.forward(is_train=False)[0].asnumpy()
    # fused lstm applies forget_bias=1.0 by convention only through bias
    # init; both paths here share identical raw weights → identical output
    np.testing.assert_allclose(f_res, u_res, rtol=1e-4, atol=1e-4)


def test_fused_pack_unpack_roundtrip():
    fused = mx.rnn.FusedRNNCell(6, num_layers=2, mode="gru", prefix="gru_",
                                bidirectional=True)
    psize = mx.ops.rnn.rnn_param_size(2, 5, 6, "gru", True)
    rng = np.random.RandomState(0)
    packed = {"gru_parameters": mx.nd.array(rng.uniform(-1, 1, (psize,)))}
    unpacked = fused.unpack_weights(packed)
    assert "gru_parameters" not in unpacked
    repacked = fused.pack_weights(unpacked)
    np.testing.assert_allclose(repacked["gru_parameters"].asnumpy(),
                               packed["gru_parameters"].asnumpy(), rtol=1e-6)


def test_encode_sentences_and_bucket_iter():
    sentences = [["the", "cat", "sat"], ["the", "dog", "ran", "far"],
                 ["a", "cat"], ["the", "cat", "sat"], ["a", "dog", "ran"],
                 ["the", "dog", "sat"]]
    coded, vocab = mx.rnn.encode_sentences(sentences, start_label=1)
    assert all(isinstance(i, int) for s in coded for i in s)
    assert vocab["the"] != vocab["cat"]

    it = mx.rnn.BucketSentenceIter(coded, batch_size=2, buckets=[3, 4],
                                   invalid_label=0)
    batches = list(it)
    assert batches, "no batches produced"
    for b in batches:
        key = b.bucket_key
        assert b.data[0].shape == (2, key)
        assert b.label[0].shape == (2, key)
        d = b.data[0].asnumpy()
        lab = b.label[0].asnumpy()
        # label is data shifted left by one
        np.testing.assert_allclose(lab[:, :-1], d[:, 1:])
    it.reset()
    assert len(list(it)) == len(batches)


def test_rnn_checkpoint_roundtrip(tmp_path):
    cell = mx.rnn.LSTMCell(4, prefix="lstm_")
    data = mx.sym.Variable("data")
    out, _ = cell.unroll(2, data, layout="NTC", merge_outputs=True)
    arg_shapes, _, _ = out.infer_shape(data=(1, 2, 3))
    rng = np.random.RandomState(0)
    args = {n: mx.nd.array(rng.uniform(-1, 1, s))
            for n, s in zip(out.list_arguments(), arg_shapes)
            if n != "data"}
    prefix = str(tmp_path / "model")
    mx.rnn.save_rnn_checkpoint(cell, prefix, 3, out, args, {})
    sym2, arg2, aux2 = mx.rnn.load_rnn_checkpoint(cell, prefix, 3)
    for k, v in args.items():
        np.testing.assert_allclose(arg2[k].asnumpy(), v.asnumpy(),
                                   rtol=1e-6)
