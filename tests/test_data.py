"""gluon.data + recordio tests (mirrors tests/python/unittest/test_gluon_data.py
and test_recordio.py from the reference)."""
import os
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import recordio
from incubator_mxnet_tpu.gluon import data as gdata


def test_recordio_roundtrip(tmp_path):
    frec = str(tmp_path / "test.rec")
    N = 10
    writer = recordio.MXRecordIO(frec, "w")
    for i in range(N):
        writer.write(b"record_%d" % i)
    writer.close()
    reader = recordio.MXRecordIO(frec, "r")
    for i in range(N):
        assert reader.read() == b"record_%d" % i
    assert reader.read() is None
    reader.close()


def test_recordio_embedded_magic(tmp_path):
    # payloads containing the magic must round-trip via the split encoding
    frec = str(tmp_path / "magic.rec")
    import struct
    payload = b"abc" + struct.pack("<I", 0xCED7230A) + b"def" + \
        struct.pack("<I", 0xCED7230A)
    w = recordio.MXRecordIO(frec, "w")
    w.write(payload)
    w.close()
    r = recordio.MXRecordIO(frec, "r")
    assert r.read() == payload
    r.close()


def test_indexed_recordio(tmp_path):
    frec = str(tmp_path / "test.rec")
    fidx = str(tmp_path / "test.idx")
    N = 8
    writer = recordio.MXIndexedRecordIO(fidx, frec, "w")
    for i in range(N):
        writer.write_idx(i, b"record_%d" % i)
    writer.close()
    reader = recordio.MXIndexedRecordIO(fidx, frec, "r")
    for i in reversed(range(N)):
        assert reader.read_idx(i) == b"record_%d" % i
    reader.close()


def test_irheader_pack_unpack():
    s = b"\x01\x02\x03payload"
    hdr = recordio.IRHeader(0, 3.5, 7, 0)
    packed = recordio.pack(hdr, s)
    hdr2, s2 = recordio.unpack(packed)
    assert hdr2.label == 3.5 and hdr2.id == 7 and s2 == s
    # multi-label
    hdr = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], dtype=np.float32), 9, 0)
    packed = recordio.pack(hdr, s)
    hdr2, s2 = recordio.unpack(packed)
    assert hdr2.flag == 3 and np.allclose(hdr2.label, [1, 2, 3]) and s2 == s


def test_pack_img_npy_roundtrip():
    img = (np.random.rand(8, 9, 3) * 255).astype(np.uint8)
    buf = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                            img_fmt=".npy")
    hdr, img2 = recordio.unpack_img(buf)
    assert np.array_equal(img, img2)


def test_array_dataset_and_loader():
    X = np.random.rand(20, 3).astype(np.float32)
    y = np.arange(20).astype(np.float32)
    ds = gdata.ArrayDataset(X, y)
    assert len(ds) == 20
    x0, y0 = ds[3]
    assert np.allclose(x0, X[3]) and y0 == 3
    loader = gdata.DataLoader(ds, batch_size=6, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 3)
    assert batches[-1][0].shape == (2, 3)
    # discard
    loader = gdata.DataLoader(ds, batch_size=6, last_batch="discard")
    assert len(list(loader)) == 3
    # rollover keeps remainder for next epoch
    loader = gdata.DataLoader(ds, batch_size=6, last_batch="rollover")
    assert len(list(loader)) == 3
    assert len(list(loader)) == 3


def test_dataloader_shuffle_covers_all():
    X = np.arange(30).astype(np.float32).reshape(30, 1)
    ds = gdata.ArrayDataset(X)
    loader = gdata.DataLoader(ds, batch_size=10, shuffle=True)
    seen = np.concatenate([b.asnumpy().ravel() for b in loader])
    assert sorted(seen.tolist()) == list(range(30))


def test_dataloader_thread_workers():
    X = np.random.rand(16, 4).astype(np.float32)
    ds = gdata.ArrayDataset(X, np.arange(16).astype(np.float32))
    loader = gdata.DataLoader(ds, batch_size=4, num_workers=2,
                              thread_pool=True)
    batches = list(loader)
    assert len(batches) == 4
    got = np.concatenate([b[1].asnumpy() for b in batches])
    assert sorted(got.tolist()) == list(range(16))


def test_dataset_transform_and_combinators():
    X = np.arange(10).astype(np.float32)
    ds = gdata.ArrayDataset(X, X * 2)
    t = ds.transform_first(lambda x: x + 100)
    a, b = t[4]
    assert a == 104 and b == 8
    sh = ds.shard(3, 0)
    assert len(sh) == 4  # 10 = 4+3+3
    assert len(ds.shard(3, 2)) == 3
    tk = ds.take(3)
    assert len(tk) == 3
    flt = gdata.SimpleDataset(list(range(10))).filter(lambda x: x % 2 == 0)
    assert len(flt) == 5


def test_record_file_dataset(tmp_path):
    frec = str(tmp_path / "img.rec")
    fidx = str(tmp_path / "img.idx")
    writer = recordio.MXIndexedRecordIO(fidx, frec, "w")
    imgs = []
    for i in range(5):
        img = (np.random.rand(4, 4, 3) * 255).astype(np.uint8)
        imgs.append(img)
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".npy"))
    writer.close()
    ds = gdata.vision.ImageRecordDataset(frec)
    assert len(ds) == 5
    img, label = ds[2]
    assert label == 2.0
    assert np.array_equal(img.asnumpy(), imgs[2])


def test_transforms():
    T = gdata.vision.transforms
    img = (np.random.rand(10, 12, 3) * 255).astype(np.uint8)
    x = mx.nd.array(img, dtype="uint8")
    t = T.ToTensor()(x)
    assert t.shape == (3, 10, 12)
    assert t.asnumpy().max() <= 1.0
    n = T.Normalize(mean=(0.5, 0.5, 0.5), std=(0.1, 0.2, 0.3))(t)
    ref = (img.transpose(2, 0, 1) / 255.0 - np.array([0.5, 0.5, 0.5])[:, None, None]) \
        / np.array([0.1, 0.2, 0.3])[:, None, None]
    assert np.allclose(n.asnumpy(), ref, atol=1e-5)
    r = T.Resize((6, 5))(x)
    assert r.shape == (5, 6, 3)
    c = T.CenterCrop(4)(x)
    assert c.shape == (4, 4, 3)
    rrc = T.RandomResizedCrop(8)(x)
    assert rrc.shape == (8, 8, 3)
    comp = T.Compose([T.Resize(8), T.ToTensor()])
    out = comp(x)
    assert out.shape == (3, 8, 8)
    for tr in [T.RandomFlipLeftRight(), T.RandomFlipTopBottom(),
               T.RandomBrightness(0.1), T.RandomContrast(0.1),
               T.RandomSaturation(0.1), T.RandomHue(0.1),
               T.RandomColorJitter(0.1, 0.1, 0.1, 0.1),
               T.RandomLighting(0.1)]:
        out = tr(x)
        assert out.shape == x.shape


def test_mnist_format_parse(tmp_path):
    # write a tiny idx-ubyte pair and parse through the MNIST dataset class
    import struct
    root = tmp_path / "mnist"
    root.mkdir()
    imgs = (np.random.rand(7, 28, 28) * 255).astype(np.uint8)
    labels = np.arange(7, dtype=np.uint8)
    with open(root / "train-images-idx3-ubyte", "wb") as f:
        f.write(struct.pack(">IIII", 2051, 7, 28, 28))
        f.write(imgs.tobytes())
    with open(root / "train-labels-idx1-ubyte", "wb") as f:
        f.write(struct.pack(">II", 2049, 7))
        f.write(labels.tobytes())
    ds = gdata.vision.MNIST(root=str(root), train=True)
    assert len(ds) == 7
    img, label = ds[3]
    assert img.shape == (28, 28, 1)
    assert label == 3
    assert np.array_equal(img.asnumpy()[..., 0], imgs[3])


def test_image_folder_dataset(tmp_path):
    root = tmp_path / "folders"
    for cls in ["cat", "dog"]:
        (root / cls).mkdir(parents=True)
    a = (np.random.rand(5, 5, 3) * 255).astype(np.uint8)
    np.save(root / "cat" / "a.npy", a)
    np.save(root / "dog" / "b.npy", a + 1 if a.max() < 255 else a)
    ds = gdata.vision.ImageFolderDataset(str(root))
    assert ds.synsets == ["cat", "dog"]
    assert len(ds) == 2
    img, label = ds[0]
    assert label == 0 and img.shape == (5, 5, 3)


def test_crop_resize_transform():
    """CropResize (reference transforms.py:238): exact fixed-window crop,
    optional resize, batch passthrough."""
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.data.vision import transforms

    rng = np.random.RandomState(0)
    img = nd.array(rng.randint(0, 255, (32, 32, 3)).astype(np.uint8))
    out = transforms.CropResize(2, 4, 10, 8)(img)
    assert out.shape == (8, 10, 3) and out.dtype == np.uint8
    np.testing.assert_array_equal(out.asnumpy(), img.asnumpy()[4:12, 2:12])
    # resize + batch
    t = transforms.CropResize(0, 0, 16, 16, size=(8, 8))
    batch = nd.array(rng.randint(0, 255, (2, 32, 32, 3)).astype(np.uint8))
    assert t(batch).shape == (2, 8, 8, 3)


def test_filter_sampler_and_dataset_sample():
    """FilterSampler (sampler.py:73) + Dataset.sample (dataset.py:119):
    predicate-selected indices, and a dataset view in sampler order."""
    ds = gdata.SimpleDataset(list(range(10)))
    s = gdata.FilterSampler(lambda x: x % 3 == 0, ds)
    assert list(s) == [0, 3, 6, 9] and len(s) == 4
    view = ds.sample(s)
    assert len(view) == 4 and [view[i] for i in range(4)] == [0, 3, 6, 9]
    # contrib IntervalSampler drives Dataset.sample too
    from incubator_mxnet_tpu.gluon.contrib.data import IntervalSampler
    view2 = ds.sample(IntervalSampler(10, 5))
    assert [view2[i] for i in range(10)] == [0, 5, 1, 6, 2, 7, 3, 8, 4, 9]
