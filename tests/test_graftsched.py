"""graftsched: per-site pass schedules with verified receipts, searched
jointly by the autotuner (analysis/passes.py PassSchedule +
analysis/autotune.py autotune_train_schedules; docs/PASSES.md
"Schedules").

Contracts under test:

- site-aware passes enumerate STABLE site ids (eqn paths into the
  inlined jaxpr) — identical across two independent traces of the same
  program;
- ``PassSchedule`` canonicalization: site order never changes the
  hash, ``from_dict(canonical())`` round-trips, the all-sites schedule
  hashes identically to the legacy ``passes=`` tuple it desugars to;
- a partial schedule installs exactly the enabled sites, the receipt
  carries one row per site, and the per-site deltas SUM to the
  whole-receipt cost delta (1 % acceptance bound; exact by
  construction);
- the all-sites schedule is bitwise-equivalent to the legacy on/off
  path (same losses, same compile-cache key → warm hit);
- schedule-keyed compile caching: same program + different schedule →
  distinct CompileCache entries; identical schedule → cross-process
  hit at ZERO XLA compiles;
- ``autotune_train_schedules``: 100 % ledger accounting, rejected
  candidates carry ``zero_compile=True`` with zero compiles spent, and
  on the bench ResNet the searched winner strictly beats the
  hand-built PR-14 ``space_to_depth,maxpool_bwd_mask`` composition on
  predicted bytes/img — all through ``analyze_cost``-grade abstract
  traces, no XLA compile.

Budget discipline: the ResNet leg is abstract-trace only (the same
scale test_fused_step_composed.py already pays); everything else runs
on the tiny dense nets.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.analysis.autotune import (autotune_train_schedules,
                                                   default_schedule_space,
                                                   dense_workload,
                                                   schedule_site_table)
from incubator_mxnet_tpu.analysis.passes import (PassContext, PassManager,
                                                 PassSchedule, get_pass,
                                                 resolve_schedule)
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.model_zoo import vision
from incubator_mxnet_tpu.parallel import aot, make_train_step
from incubator_mxnet_tpu.parallel.distributed import collectives_supported

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BENCH_PASSES = ("space_to_depth", "maxpool_bwd_mask")  # the PR-14 pair


def _mlp_program(seed=7):
    """Abstract inference jaxpr of the 2-layer test MLP + its param
    values (probe overrides) — the direct-PassManager harness."""
    from incubator_mxnet_tpu.gluon.block import pure_forward

    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, 16)))
    params = list(net.collect_params().values())
    p_vals = [p._data._data for p in params]

    def infer(pv, x):
        out, _tc = pure_forward(net, params, pv, x, training=False)
        return out

    closed = jax.make_jaxpr(infer)(
        [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in p_vals],
        jax.ShapeDtypeStruct((4, 16), np.float32))
    ctx = PassContext(param_invars=frozenset(range(len(p_vals))),
                      probe_overrides=dict(enumerate(p_vals)),
                      where="test_graftsched")
    return closed, ctx


def _amp_step(schedule=None, seed=3, **kw):
    """3x Dense(16) train step with amp_bf16 — ``schedule`` may be a
    legacy name tuple, a PassSchedule or a canonical dict (the
    subprocess leg re-hydrates from JSON)."""
    mx.random.seed(seed)
    net = nn.HybridSequential()
    for _ in range(3):
        net.add(nn.Dense(16, activation="tanh"))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, 16)))
    step = make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                           optimizer="sgd", learning_rate=0.1, momentum=0.9,
                           lint="off", cost="off",
                           passes=schedule if schedule is not None
                           else ("amp_bf16",), **kw)
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(8, 16).astype(np.float32))
    y = nd.array((np.arange(8) % 4).astype(np.float32))
    return step, x, y


# ---------------------------------------------------------------------------
# site enumeration + schedule canonicalization
# ---------------------------------------------------------------------------

def test_site_enumeration_stable_ids():
    closed, ctx = _mlp_program()
    amp = get_pass("amp_bf16")
    q8 = get_pass("quantize_int8")
    assert amp.site_aware and q8.site_aware
    ids = [s.id for s in amp.enumerate_sites(closed, ctx)]
    assert ids == ["dot_general:0", "dot_general:1"]
    qids = [s.id for s in q8.enumerate_sites(closed, ctx)]
    assert qids and all(i.startswith("invar:") for i in qids)
    # stability across an independent retrace of the same model
    closed2, ctx2 = _mlp_program()
    assert [s.id for s in amp.enumerate_sites(closed2, ctx2)] == ids
    assert [s.id for s in q8.enumerate_sites(closed2, ctx2)] == qids
    # sites carry the local unfused weights the delta attribution uses
    s0 = amp.enumerate_sites(closed, ctx)[0]
    assert s0.kind == "eqn" and s0.flops > 0 and s0.hbm_bytes > 0


def test_schedule_canonical_hash_roundtrip():
    a = PassSchedule([("amp_bf16", {"dot_general:0": True,
                                    "dot_general:1": False}),
                      ("cse_dead_aux", True)])
    b = PassSchedule([("amp_bf16", {"dot_general:1": False,
                                    "dot_general:0": True}),
                      ("cse_dead_aux", True)])
    assert a.hash() == b.hash()  # site order never changes the hash
    assert PassSchedule.from_dict(a.canonical()).hash() == a.hash()
    # the legacy passes= tuple IS the all-sites schedule
    legacy = PassSchedule.from_passes(("amp_bf16", "cse_dead_aux"))
    allon = PassSchedule([("amp_bf16", True), ("cse_dead_aux", True)])
    assert legacy.hash() == allon.hash()
    # two different schedules never share a hash
    assert a.hash() != allon.hash()
    off = PassSchedule([("amp_bf16", False), ("cse_dead_aux", True)])
    assert off.hash() != allon.hash()
    assert not off.enabled("amp_bf16") and off.enabled("cse_dead_aux")
    assert a.sites_for("amp_bf16") == frozenset({"dot_general:0"})
    # resolve_schedule: dict and PassSchedule in, (passes, schedule) out
    ps, sched = resolve_schedule(a.canonical())
    assert [p.name for p in ps] == ["amp_bf16", "cse_dead_aux"]
    assert sched.hash() == a.hash()
    ps2, sched2 = resolve_schedule("amp_bf16,cse_dead_aux")
    assert sched2 is None and [p.name for p in ps2] == ["amp_bf16",
                                                        "cse_dead_aux"]


# ---------------------------------------------------------------------------
# partial schedules: receipts, per-site delta attribution
# ---------------------------------------------------------------------------

def test_partial_schedule_installs_enabled_sites_only():
    closed, ctx = _mlp_program()
    sched = PassSchedule([("amp_bf16", {"dot_general:1": True})])
    res = PassManager(None, schedule=sched, raise_on_error=False).run(
        closed, ctx)
    (r,) = res.receipts
    assert r.installed and r.hits == 1
    rows = {row["site"]: row for row in r.sites}
    assert rows["dot_general:0"]["decision"] is False
    assert not rows["dot_general:0"]["installed"]
    assert rows["dot_general:0"]["hbm_bytes_delta"] == 0.0
    assert rows["dot_general:1"]["decision"] is True
    assert rows["dot_general:1"]["installed"]


def test_per_site_deltas_sum_to_receipt_delta():
    """Acceptance bound: per-site receipts sum to the whole-schedule
    CostReport delta within 1 % (exact by construction)."""
    closed, ctx = _mlp_program()
    res = PassManager(["quantize_int8", "amp_bf16"]).run(closed, ctx)
    for r in res.receipts:
        assert r.installed, r.name
        assert r.sites, r.name
        for field in ("hbm_bytes", "flops", "param_bytes"):
            whole = getattr(r, field + "_after") - \
                getattr(r, field + "_before")
            part = sum(row[field + "_delta"] for row in r.sites)
            tol = max(abs(whole) * 0.01, 1e-6)
            assert abs(part - whole) <= tol, (r.name, field, part, whole)
        # installed sites with a concrete probe report probe_ok=True
        assert all(row["probe_ok"] for row in r.sites
                   if row["installed"]), r.name


def test_disabled_pass_and_gl304_no_match():
    closed, ctx = _mlp_program()
    # whole pass off: a deliberate decision, NOT a GL304 no-op warning
    sched = PassSchedule([("amp_bf16", False)])
    res = PassManager(None, schedule=sched, raise_on_error=False).run(
        closed, ctx)
    assert not res.receipts[0].installed
    assert "disabled by schedule" in (res.receipts[0].notes or "")
    assert not any(d.code == "GL304" for d in res.diagnostics)
    # a schedule naming sites that do not exist IS a GL304 no-op
    ghost = PassSchedule([("amp_bf16", {"dot_general:99": True})])
    res2 = PassManager(None, schedule=ghost, raise_on_error=False).run(
        closed, ctx)
    assert not res2.receipts[0].installed
    assert any(d.code == "GL304" for d in res2.diagnostics)


# ---------------------------------------------------------------------------
# all-sites schedule == legacy passes= (sugar, bitwise)
# ---------------------------------------------------------------------------

def test_all_sites_schedule_bitwise_equals_legacy(tmp_path):
    cache = aot.CompileCache(str(tmp_path))
    step_a, x, y = _amp_step(("amp_bf16",))
    assert step_a.aot_compile(x, y, cache=cache)["cache"] == "stored"
    losses_a = [float(step_a(x, y).asscalar()) for _ in range(3)]

    sched = PassSchedule.from_passes(("amp_bf16",))
    step_b, x2, y2 = _amp_step(sched)
    assert step_b.schedule_hash == step_a.schedule_hash
    c0 = aot.XLA_COMPILES.count
    t = step_b.aot_compile(x2, y2, cache=cache)
    assert t["cache"] == "hit"  # same program, same schedule key
    assert aot.XLA_COMPILES.count == c0
    losses_b = [float(step_b(x2, y2).asscalar()) for _ in range(3)]
    assert losses_a == losses_b  # bitwise: the on/off path is sugar


# ---------------------------------------------------------------------------
# schedule-keyed compile caching
# ---------------------------------------------------------------------------

def test_different_schedules_distinct_cache_entries(tmp_path):
    """Two schedules of the SAME pass list never collide in the
    compile cache — even when they lower to the same bytes."""
    cache = aot.CompileCache(str(tmp_path))
    step_a, x, y = _amp_step(PassSchedule.from_passes(("amp_bf16",)))
    partial = PassSchedule([("amp_bf16", {"dot_general:0": True})])
    step_b, _, _ = _amp_step(partial)
    assert step_a.schedule_hash != step_b.schedule_hash
    assert step_a._cache_extra() != step_b._cache_extra()
    assert step_a.aot_compile(x, y, cache=cache)["cache"] == "stored"
    t = step_b.aot_compile(x, y, cache=cache)
    assert t["cache"] == "stored"  # distinct entry, no false hit
    assert cache.hits == 0


def test_same_schedule_cross_process_zero_compiles(tmp_path):
    """A fresh process rebuilding the SAME partial schedule performs 0
    XLA compiles (the retune-after-restart contract)."""
    if not collectives_supported():
        pytest.skip("backend cannot run the subprocess leg")
    sched = PassSchedule([("amp_bf16", {"dot_general:0": True,
                                        "dot_general:1": True,
                                        "dot_general:2": False})])
    cache = aot.CompileCache(str(tmp_path))
    step, x, y = _amp_step(sched)
    assert step.aot_compile(x, y, cache=cache)["cache"] == "stored"
    loss_ref = float(step(x, y).asscalar())

    child = subprocess.run(
        [sys.executable, "-c", """
import sys, json
sys.path.insert(0, %r)
from _platform_pin import pin_cpu
jax = pin_cpu(8)
jax.config.update("jax_default_matmul_precision", "highest")
from tests.test_graftsched import _amp_step
from incubator_mxnet_tpu.analysis.passes import PassSchedule
from incubator_mxnet_tpu.parallel import aot
sched = PassSchedule.from_dict(json.loads(%r))
step, x, y = _amp_step(sched)
t = step.aot_compile(x, y)
print(json.dumps({"cache": t["cache"], "compiles": aot.XLA_COMPILES.count,
                  "sched": step.schedule_hash,
                  "loss": float(step(x, y).asscalar())}))
""" % (REPO, sched.to_json())],
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 MXTPU_COMPILE_CACHE=str(tmp_path)),
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert child.returncode == 0, child.stderr[-2000:]
    rec = json.loads(child.stdout.strip().splitlines()[-1])
    assert rec["sched"] == sched.hash()
    assert rec["cache"] == "hit"
    assert rec["compiles"] == 0  # ZERO XLA compiles in the new process
    assert rec["loss"] == loss_ref


# ---------------------------------------------------------------------------
# the joint search
# ---------------------------------------------------------------------------

def test_schedule_search_ledger_and_winner_config():
    mk, mb, loss_fn = dense_workload()
    c0 = aot.XLA_COMPILES.count
    res = autotune_train_schedules(mk, mb, loss_fn,
                                   passes=("cse_dead_aux", "amp_bf16"),
                                   knobs={"batch": 8}, device="cpu-proxy",
                                   budget_compiles=0)
    assert aot.XLA_COMPILES.count == c0  # ranking never compiles
    assert res.compiles_spent == 0
    assert res.candidates and all(c.zero_compile for c in res.candidates)
    assert all(c.status == "predicted" for c in res.candidates)
    hashes = [c.knobs["schedule_hash"] for c in res.candidates]
    assert len(set(hashes)) == len(hashes)  # deduped space
    cfg = res.winner_config()  # predicted-only winner (budget 0)
    assert cfg is not None and cfg["knobs"]["schedule_hash"] in hashes
    assert cfg["measured_s_per_sample"] is None
    # the persisted schedule round-trips into a runnable step
    ps, sched = resolve_schedule(cfg["knobs"]["schedule"])
    assert sched.hash() == cfg["knobs"]["schedule_hash"]


def test_schedule_search_rejects_over_budget_zero_compile():
    mk, mb, loss_fn = dense_workload()
    c0 = aot.XLA_COMPILES.count
    res = autotune_train_schedules(mk, mb, loss_fn,
                                   passes=("cse_dead_aux", "amp_bf16"),
                                   knobs={"batch": 8}, device="cpu-proxy",
                                   hbm_budget=1.0,  # 1 byte: nothing fits
                                   budget_compiles=0)
    assert aot.XLA_COMPILES.count == c0
    rejected = [c for c in res.candidates
                if c.status == "rejected-infeasible"]
    assert rejected and all(c.zero_compile for c in rejected)
    assert all("GL201" in (c.reason or "") for c in rejected)
    assert res.winner is None and res.winner_config() is None


# ---------------------------------------------------------------------------
# the acceptance leg: bench ResNet, searched vs the hand-built PR-14 pair
# ---------------------------------------------------------------------------

def _resnet_workload(img=112, classes=1000):
    def make_net(knobs):
        mx.random.seed(0)
        # ghost_bn=16: the bench default (DEFAULT_GHOST_BN) — the
        # config where maxpool_bwd_mask has its rewrite target
        net = vision.resnet50_v1(classes=classes, ghost_bn=16)
        net.initialize(init=mx.init.Zero())  # shapes only
        net.shape_init((1, 3, img, img))
        return net

    def make_batch(knobs):
        b = int(knobs.get("batch", 32))
        return (jax.ShapeDtypeStruct((b, 3, img, img), np.float32),
                jax.ShapeDtypeStruct((b,), np.float32))

    return make_net, make_batch, gluon.loss.SoftmaxCrossEntropyLoss()


def test_searched_schedule_beats_pr14_composition_on_bench_resnet():
    """A searched per-site schedule strictly beats the hand-built PR-14
    ``space_to_depth,maxpool_bwd_mask`` composition on predicted
    bytes/img for the bench ResNet — ranked from ONE abstract site
    table, zero XLA compiles spent on the whole search."""
    B, IMG = 32, 112
    mk, mb, loss_fn = _resnet_workload(img=IMG)
    knobs = {"batch": B}

    # the hand-built composition, costed exactly as bench does: the
    # pass-rewritten program through analyze_cost (no compile)
    net = mk(knobs)
    pr14 = make_train_step(net, loss_fn, optimizer="sgd",
                           learning_rate=0.1, momentum=0.9, wd=1e-4,
                           lint="off", cost="off", passes=BENCH_PASSES)
    x, y = mb(knobs)
    pr14_rep = pr14.analyze_cost(x, y, device="tpu-v5e")
    pr14_bytes_img = pr14_rep.hbm_bytes / B

    c0 = aot.XLA_COMPILES.count
    res = autotune_train_schedules(
        mk, mb, loss_fn,
        passes=BENCH_PASSES + ("cse_dead_aux", "amp_bf16"),
        knobs=dict(knobs), device="tpu-v5e", budget_compiles=0)
    assert aot.XLA_COMPILES.count == c0  # the search never compiled
    assert all(c.zero_compile for c in res.candidates)
    predicted = [c for c in res.candidates if c.status == "predicted"]
    assert predicted
    best = min(predicted, key=lambda c: c.pred["hbm_bytes"])
    best_bytes_img = best.pred["hbm_bytes"] / B
    # strict byte win over the hand-built pair
    assert best_bytes_img < pr14_bytes_img, (best_bytes_img,
                                             pr14_bytes_img)
    # and the winner is a real schedule bench/serve can load
    sched = PassSchedule.from_dict(best.knobs["schedule"])
    assert sched.hash() == best.knobs["schedule_hash"]
