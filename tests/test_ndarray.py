"""NDArray core tests (reference: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.array([[1, 2], [3, 4]])
    assert c.asnumpy().tolist() == [[1.0, 2.0], [3.0, 4.0]]
    d = nd.full((2, 2), 7.0)
    assert d.asnumpy()[0, 0] == 7.0
    e = nd.arange(0, 10, 2)
    np.testing.assert_allclose(e.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    np.testing.assert_allclose((a * b).asnumpy(), [[10, 40], [90, 160]])
    np.testing.assert_allclose((b / a).asnumpy(), [[10, 10], [10, 10]])
    np.testing.assert_allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((1 - a).asnumpy(), [[0, -1], [-2, -3]])
    np.testing.assert_allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]], rtol=1e-5)
    np.testing.assert_allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])


def test_inplace():
    a = nd.ones((2, 2))
    a += 1
    np.testing.assert_allclose(a.asnumpy(), 2 * np.ones((2, 2)))
    a *= 3
    np.testing.assert_allclose(a.asnumpy(), 6 * np.ones((2, 2)))


def test_indexing():
    a = nd.array(np.arange(12).reshape(3, 4))
    np.testing.assert_allclose(a[1].asnumpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(a[1:3, 2].asnumpy(), [6, 10])
    a[0, 0] = 99.0
    assert a.asnumpy()[0, 0] == 99.0
    a[1] = 0.0
    np.testing.assert_allclose(a.asnumpy()[1], np.zeros(4))


def test_broadcast_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    np.testing.assert_allclose((a > b).asnumpy(), [0, 0, 1])
    np.testing.assert_allclose((a == b).asnumpy(), [0, 1, 0])


def test_reshape_transpose():
    a = nd.array(np.arange(6).reshape(2, 3))
    assert a.reshape(3, 2).shape == (3, 2)
    assert a.reshape((-1,)).shape == (6,)
    assert a.T.shape == (3, 2)
    assert a.reshape(0, -1).shape == (2, 3)
    assert a.expand_dims(0).shape == (1, 2, 3)
    assert a.flatten().shape == (2, 3)


def test_mx_reshape_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((0, -3)).shape == (2, 12)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)
    assert a.reshape((6, 1, -1)).shape == (6, 1, 4)


def test_reductions():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert a.sum().asscalar() == 66.0
    np.testing.assert_allclose(a.sum(axis=0).asnumpy(), [12, 15, 18, 21])
    np.testing.assert_allclose(a.mean(axis=1).asnumpy(), [1.5, 5.5, 9.5])
    assert a.max().asscalar() == 11.0
    assert a.min().asscalar() == 0.0
    # exclude semantics
    np.testing.assert_allclose(
        nd.sum(a, axis=0, exclude=True).asnumpy(), a.asnumpy().sum(axis=1))


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    np.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                               a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    np.testing.assert_allclose(
        nd.dot(a, b.T, transpose_b=True).asnumpy()[0, 0],
        (a.asnumpy() @ b.asnumpy())[0, 0], rtol=1e-5)


def test_concat_stack_split():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    assert nd.concat(a, b, dim=0).shape == (4, 3)
    assert nd.concat(a, b, dim=1).shape == (2, 6)
    assert nd.stack(a, b, axis=0).shape == (2, 2, 3)
    parts = nd.split(nd.ones((4, 6)), num_outputs=2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (4, 3)


def test_take_one_hot_where():
    w = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array([0, 2])
    np.testing.assert_allclose(nd.take(w, idx).asnumpy(), [[0, 1, 2], [6, 7, 8]])
    oh = nd.one_hot(nd.array([0, 2]), depth=3)
    np.testing.assert_allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])
    cond = nd.array([1.0, 0.0])
    np.testing.assert_allclose(
        nd.where(cond, nd.array([1.0, 2.0]), nd.array([3.0, 4.0])).asnumpy(),
        [1.0, 4.0])


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    np.testing.assert_allclose(nd.topk(a, k=2, ret_typ="value").asnumpy(),
                               [[3, 2], [5, 4]])
    np.testing.assert_allclose(nd.sort(a, is_ascend=True).asnumpy(),
                               [[1, 2, 3], [0, 4, 5]])
    np.testing.assert_allclose(a.argmax(axis=1).asnumpy(), [0, 1])


def test_cast_astype():
    a = nd.array([1.5, 2.5])
    assert a.astype("int32").dtype == np.int32
    assert nd.cast(a, dtype="float64").dtype == np.float64


def test_save_load(tmp_path):
    f = str(tmp_path / "arrs")
    a, b = nd.ones((2, 2)), nd.zeros((3,))
    nd.save(f, [a, b])
    out = nd.load(f)
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_allclose(out[0].asnumpy(), a.asnumpy())
    nd.save(f, {"w": a, "b": b})
    d = nd.load(f)
    assert set(d.keys()) == {"w", "b"}
    np.testing.assert_allclose(d["b"].asnumpy(), b.asnumpy())


def test_random():
    mx.random.seed(7)
    a = nd.random.uniform(0, 1, shape=(100,))
    mx.random.seed(7)
    b = nd.random.uniform(0, 1, shape=(100,))
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    c = nd.random.normal(0, 1, shape=(1000,))
    assert abs(float(c.mean().asscalar())) < 0.2
    r = nd.random.randint(0, 10, shape=(50,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10


def test_wait_to_read_and_context():
    a = nd.ones((2, 2), ctx=mx.cpu())
    a.wait_to_read()
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b.shape == (2, 2)


def test_norm_clip():
    a = nd.array([3.0, 4.0])
    assert abs(a.norm().asscalar() - 5.0) < 1e-5
    np.testing.assert_allclose(a.clip(0, 3.5).asnumpy(), [3.0, 3.5])


def test_sequence_ops():
    data = nd.array(np.arange(24, dtype=np.float32).reshape(4, 3, 2))
    lens = nd.array([2, 3, 1])
    masked = nd.SequenceMask(data, lens, use_sequence_length=True, value=-1.0)
    out = masked.asnumpy()
    assert (out[2:, 0] == -1).all() and (out[3:, 1] == -1).all()
    last = nd.SequenceLast(data, lens, use_sequence_length=True)
    np.testing.assert_allclose(last.asnumpy()[0], data.asnumpy()[1, 0])
