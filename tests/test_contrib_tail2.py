"""Round-3 operator long tail (ops/contrib_tail.py): spatial warping,
deformable conv, proposals, fused transformer matmuls, fft/count_sketch,
masking/index ops."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.ops import registry as reg


def _inv(name, arrays, **attrs):
    import jax.numpy as jnp

    op = reg.get_op(name)
    return op.fn(*[None if a is None else jnp.asarray(a) for a in arrays],
                 **attrs)


def test_grid_generator_affine_identity():
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    grid = np.asarray(_inv("GridGenerator", [theta],
                           transform_type="affine", target_shape=(4, 5)))
    assert grid.shape == (2, 2, 4, 5)
    np.testing.assert_allclose(grid[0, 0, 0], np.linspace(-1, 1, 5),
                               atol=1e-6)
    np.testing.assert_allclose(grid[0, 1, :, 0], np.linspace(-1, 1, 4),
                               atol=1e-6)


def test_bilinear_sampler_identity():
    rng = np.random.RandomState(0)
    data = rng.rand(2, 3, 6, 7).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    grid = _inv("GridGenerator", [theta], transform_type="affine",
                target_shape=(6, 7))
    out = np.asarray(_inv("BilinearSampler", [data, np.asarray(grid)]))
    np.testing.assert_allclose(out, data, rtol=1e-4, atol=1e-5)


def test_spatial_transformer_shift():
    data = np.zeros((1, 1, 5, 5), np.float32)
    data[0, 0, 2, 2] = 1.0
    # translate by +2/(W-1)*2... affine tx shifts sampling grid right
    theta = np.array([[1, 0, 0.5, 0, 1, 0]], np.float32)
    out = np.asarray(_inv("SpatialTransformer", [data, theta],
                          target_shape=(5, 5)))
    # sampling coords shifted right → peak appears shifted LEFT
    assert out.shape == (1, 1, 5, 5)
    assert out[0, 0, 2, 1] == pytest.approx(1.0, abs=1e-4)


def test_grid_generator_warp_zero_flow_is_identity_sampling():
    rng = np.random.RandomState(1)
    data = rng.rand(1, 2, 4, 6).astype(np.float32)
    flow = np.zeros((1, 2, 4, 6), np.float32)
    grid = _inv("GridGenerator", [flow], transform_type="warp")
    out = np.asarray(_inv("BilinearSampler", [data, np.asarray(grid)]))
    np.testing.assert_allclose(out, data, rtol=1e-4, atol=1e-5)


def test_correlation_zero_displacement_matches_product_mean():
    rng = np.random.RandomState(2)
    a = rng.rand(1, 4, 6, 6).astype(np.float32)
    out = np.asarray(_inv("Correlation", [a, a], kernel_size=1,
                          max_displacement=0, stride1=1, stride2=1,
                          pad_size=0))
    assert out.shape == (1, 1, 6, 6)
    np.testing.assert_allclose(out[0, 0], (a * a).mean(axis=1)[0],
                               rtol=1e-5)


def test_crop():
    data = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    out = np.asarray(_inv("Crop", [data], h_w=(2, 2), center_crop=True))
    np.testing.assert_array_equal(out[0, 0], data[0, 0, 2:4, 2:4])


def test_deformable_conv_zero_offset_equals_conv():
    rng = np.random.RandomState(3)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    w = rng.rand(4, 3, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 9, 8, 8), np.float32)
    got = np.asarray(_inv("_contrib_DeformableConvolution", [x, off, w],
                          kernel=(3, 3), num_filter=4, pad=(1, 1),
                          no_bias=True))
    ref = np.asarray(_inv("Convolution", [x, w, None], kernel=(3, 3),
                          num_filter=4, pad=(1, 1), no_bias=True))
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_deformable_conv_fractional_offset_interpolates():
    x = np.zeros((1, 1, 4, 4), np.float32)
    x[0, 0, 1, 1] = 1.0
    x[0, 0, 1, 2] = 3.0
    w = np.ones((1, 1, 1, 1), np.float32)
    off = np.full((1, 2, 4, 4), 0.0, np.float32)
    off[0, 1] = 0.5  # dx = +0.5
    got = np.asarray(_inv("_contrib_DeformableConvolution", [x, off, w],
                          kernel=(1, 1), num_filter=1, no_bias=True))
    assert got[0, 0, 1, 1] == pytest.approx(2.0, abs=1e-5)  # halfway 1→3


def test_proposal_shapes_and_validity():
    rng = np.random.RandomState(4)
    n, a, fh, fw = 1, 3, 4, 4
    cls = rng.rand(n, 2 * a, fh, fw).astype(np.float32)
    bbox = (rng.rand(n, 4 * a, fh, fw).astype(np.float32) - 0.5) * 0.1
    im_info = np.array([[64, 64, 1.0]], np.float32)
    rois = np.asarray(_inv("_contrib_Proposal", [cls, bbox, im_info],
                           rpn_pre_nms_top_n=12, rpn_post_nms_top_n=5,
                           threshold=0.7, rpn_min_size=4,
                           scales=(4, 8, 16), ratios=(1.0,),
                           feature_stride=16))
    assert rois.shape == (5, 5)
    assert (rois[:, 0] == 0).all()
    assert (rois[:, 1] >= 0).all() and (rois[:, 3] <= 63).all()
    assert (rois[:, 2] >= 0).all() and (rois[:, 4] <= 63).all()
    assert (rois[:, 3] >= rois[:, 1]).all()


def test_interleaved_matmul_selfatt_matches_reference_equivalent():
    rng = np.random.RandomState(5)
    s, b, heads, hd = 6, 2, 2, 4
    qkv = rng.rand(s, b, heads * hd * 3).astype(np.float32)
    scores = np.asarray(_inv("_contrib_interleaved_matmul_selfatt_qk",
                             [qkv], heads=heads))
    # reference equivalent code (transformer.cc describe block)
    tmp = qkv.reshape(s, b, heads, 3, hd)
    q = tmp[:, :, :, 0].transpose(1, 2, 0, 3).reshape(b * heads, s, hd)
    k = tmp[:, :, :, 1].transpose(1, 2, 0, 3).reshape(b * heads, s, hd)
    expect = (q / np.sqrt(hd)) @ k.transpose(0, 2, 1)
    np.testing.assert_allclose(scores, expect, rtol=1e-5, atol=1e-6)

    att = rng.rand(b * heads, s, s).astype(np.float32)
    out = np.asarray(_inv("_contrib_interleaved_matmul_selfatt_valatt",
                          [qkv, att], heads=heads))
    v = tmp[:, :, :, 2].transpose(1, 2, 0, 3).reshape(b * heads, s, hd)
    expect_out = (att @ v).reshape(b, heads, s, hd).transpose(
        2, 0, 1, 3).reshape(s, b, heads * hd)
    np.testing.assert_allclose(out, expect_out, rtol=1e-5, atol=1e-6)


def test_interleaved_matmul_encdec():
    rng = np.random.RandomState(6)
    sq, sk, b, heads, hd = 3, 5, 2, 2, 4
    q = rng.rand(sq, b, heads * hd).astype(np.float32)
    kv = rng.rand(sk, b, heads * hd * 2).astype(np.float32)
    scores = np.asarray(_inv("_contrib_interleaved_matmul_encdec_qk",
                             [q, kv], heads=heads))
    assert scores.shape == (b * heads, sq, sk)
    att = rng.rand(b * heads, sq, sk).astype(np.float32)
    out = np.asarray(_inv("_contrib_interleaved_matmul_encdec_valatt",
                          [kv, att], heads=heads))
    assert out.shape == (sq, b, heads * hd)


def test_fft_ifft_roundtrip():
    rng = np.random.RandomState(7)
    x = rng.rand(3, 8).astype(np.float32)
    spec = np.asarray(_inv("_contrib_fft", [x]))
    assert spec.shape == (3, 16)
    # interleaved layout vs numpy fft
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(spec[:, 0::2], ref.real, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(spec[:, 1::2], ref.imag, rtol=1e-4,
                               atol=1e-4)
    back = np.asarray(_inv("_contrib_ifft", [spec]))
    np.testing.assert_allclose(back, x * 8, rtol=1e-4, atol=1e-4)


def test_count_sketch():
    data = np.array([[1.0, 2.0, 3.0]], np.float32)
    h = np.array([[0, 1, 0]], np.float32)
    s = np.array([[1, -1, 1]], np.float32)
    out = np.asarray(_inv("_contrib_count_sketch", [data, h, s], out_dim=2))
    np.testing.assert_allclose(out, [[4.0, -2.0]])


def test_boolean_mask_index_copy_index_array():
    data = np.arange(12, dtype=np.float32).reshape(4, 3)
    mask = np.array([1, 0, 1, 0], np.float32)
    out = np.asarray(_inv("_contrib_boolean_mask", [data, mask]))
    np.testing.assert_array_equal(out, data[[0, 2]])

    old = np.zeros((4, 2), np.float32)
    new = np.ones((2, 2), np.float32)
    got = np.asarray(_inv("_contrib_index_copy",
                          [old, np.array([1, 3], np.float32), new]))
    assert got[1].sum() == 2 and got[3].sum() == 2 and got[0].sum() == 0

    ia = np.asarray(_inv("_contrib_index_array", [np.zeros((2, 3))]))
    assert ia.shape == (2, 3, 2)
    assert ia[1, 2, 0] == 1 and ia[1, 2, 1] == 2


def test_sync_batch_norm_matches_batch_norm():
    rng = np.random.RandomState(8)
    x = rng.rand(4, 3, 5, 5).astype(np.float32)
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    a = np.asarray(_inv("_contrib_SyncBatchNorm", [x, gamma, beta, mm, mv],
                        fix_gamma=False, ndev=4, key="sbn"))
    b = np.asarray(_inv("BatchNorm", [x, gamma, beta, mm, mv],
                        fix_gamma=False))
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_registry_count_grew():
    distinct = len({id(o) for o in reg.OPS.values()})
    assert distinct >= 275, distinct
