"""Operator math tests (reference: tests/python/unittest/test_operator.py).

Gradient correctness is checked against finite differences
(check_numeric_gradient analog, test_utils.py:981 in the reference).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd


def numeric_grad(f, x, eps=1e-3):
    x = x.astype(np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy(); xp[idx] += eps
        xm = x.copy(); xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def check_grad(op_fn, x_np, rtol=1e-2, atol=1e-3):
    x = nd.array(x_np.astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = op_fn(x).sum()
    y.backward()
    num = numeric_grad(lambda z: float(op_fn(nd.array(z.astype(np.float32))).sum().asscalar()), x_np)
    np.testing.assert_allclose(x.grad.asnumpy(), num, rtol=rtol, atol=atol)


def test_fully_connected():
    x = nd.array(np.random.rand(4, 10).astype(np.float32))
    w = nd.array(np.random.rand(5, 10).astype(np.float32))
    b = nd.array(np.random.rand(5).astype(np.float32))
    out = nd.FullyConnected(x, w, b, num_hidden=5)
    expected = x.asnumpy() @ w.asnumpy().T + b.asnumpy()
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-5)
    out2 = nd.FullyConnected(data=x, weight=w, num_hidden=5, no_bias=True)
    np.testing.assert_allclose(out2.asnumpy(), x.asnumpy() @ w.asnumpy().T, rtol=1e-5)


def test_fully_connected_grad():
    x_np = np.random.rand(3, 4).astype(np.float32)
    w = nd.array(np.random.rand(2, 4).astype(np.float32))
    b = nd.array(np.zeros(2, dtype=np.float32))
    check_grad(lambda x: nd.FullyConnected(x, w, b, num_hidden=2), x_np)


def test_convolution_shapes():
    x = nd.random.uniform(shape=(2, 3, 8, 8))
    w = nd.random.uniform(shape=(4, 3, 3, 3))
    b = nd.zeros((4,))
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    assert out.shape == (2, 4, 6, 6)
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, pad=(1, 1))
    assert out.shape == (2, 4, 8, 8)
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, stride=(2, 2), pad=(1, 1))
    assert out.shape == (2, 4, 4, 4)


def test_convolution_vs_numpy():
    # 1x1 conv == matmul over channels
    x = nd.random.uniform(shape=(2, 3, 5, 5))
    w = nd.random.uniform(shape=(4, 3, 1, 1))
    b = nd.zeros((4,))
    out = nd.Convolution(x, w, b, kernel=(1, 1), num_filter=4)
    xn = x.asnumpy(); wn = w.asnumpy()[:, :, 0, 0]
    expected = np.einsum("nchw,oc->nohw", xn, wn)
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-4, atol=1e-5)


def test_grouped_and_depthwise_conv():
    x = nd.random.uniform(shape=(1, 4, 6, 6))
    w = nd.random.uniform(shape=(4, 1, 3, 3))
    out = nd.Convolution(x, w, None, kernel=(3, 3), num_filter=4, num_group=4,
                         no_bias=True)
    assert out.shape == (1, 4, 4, 4)


def test_conv_grad():
    x_np = np.random.rand(1, 2, 5, 5).astype(np.float32)
    w = nd.array(np.random.rand(3, 2, 3, 3).astype(np.float32))
    check_grad(lambda x: nd.Convolution(x, w, None, kernel=(3, 3), num_filter=3,
                                        no_bias=True), x_np, rtol=2e-2, atol=2e-3)


def test_pooling():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    out = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    np.testing.assert_allclose(out.asnumpy()[0, 0], [[5, 7], [13, 15]])
    out = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    np.testing.assert_allclose(out.asnumpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    out = nd.Pooling(x, global_pool=True, pool_type="max")
    assert out.shape == (1, 1, 1, 1)
    assert out.asscalar() == 15.0


def test_maxpool_backward():
    # overlapping 3x3/s2 windows (the ResNet stem pool) through the
    # autograd frontend; oracle = numeric windows walked in numpy
    x_np = np.random.RandomState(3).rand(2, 3, 9, 9).astype(np.float32)
    x = nd.array(x_np)
    x.attach_grad()
    with autograd.record():
        y = nd.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
        y.backward(nd.ones_like(y))
    pad = np.full((2, 3, 11, 11), -np.inf, np.float32)
    pad[:, :, 1:10, 1:10] = x_np
    want = np.zeros_like(pad)
    for i in range(5):
        for j in range(5):
            w = pad[:, :, 2 * i:2 * i + 3, 2 * j:2 * j + 3]
            # first-match argmax in row-major scan order (pool.h
            # unpool_max_*_cpu), one winner per window
            flat = w.reshape(2, 3, -1)
            arg = flat.argmax(axis=-1)
            m = np.zeros_like(flat)
            for b in range(2):
                for c in range(3):
                    m[b, c, arg[b, c]] = 1.0
            want[:, :, 2 * i:2 * i + 3, 2 * j:2 * j + 3] += m.reshape(w.shape)
    np.testing.assert_allclose(x.grad.asnumpy(), want[:, :, 1:10, 1:10])

    # tie semantics: the whole gradient goes to the FIRST position equal
    # to the window max (reference pool.h unpool_max routes to a single
    # argmax; ties do NOT each receive the full gradient)
    t = nd.array(np.ones((1, 1, 2, 2), np.float32))
    t.attach_grad()
    with autograd.record():
        y = nd.Pooling(t, kernel=(2, 2), stride=(2, 2), pool_type="max")
        y.backward()
    want_t = np.zeros((1, 1, 2, 2), np.float32)
    want_t[0, 0, 0, 0] = 1.0
    np.testing.assert_allclose(t.grad.asnumpy(), want_t)


def test_batchnorm_inference_and_training():
    x = nd.random.normal(0, 1, shape=(8, 3, 4, 4))
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mean, var = nd.zeros((3,)), nd.ones((3,))
    out = nd.BatchNorm(x, gamma, beta, mean, var, fix_gamma=False)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), rtol=1e-2, atol=1e-2)
    with autograd.record():
        out_t = nd.BatchNorm(x, gamma, beta, mean, var, fix_gamma=False)
    o = out_t.asnumpy()
    np.testing.assert_allclose(o.mean(axis=(0, 2, 3)), np.zeros(3), atol=1e-5)
    np.testing.assert_allclose(o.std(axis=(0, 2, 3)), np.ones(3), atol=1e-2)


def test_layernorm():
    x = nd.random.normal(0, 1, shape=(4, 10))
    g, b = nd.ones((10,)), nd.zeros((10,))
    out = nd.LayerNorm(x, g, b).asnumpy()
    np.testing.assert_allclose(out.mean(axis=-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(out.std(axis=-1), np.ones(4), atol=1e-2)


def test_activation_ops():
    x = nd.array([-2.0, 0.0, 2.0])
    np.testing.assert_allclose(nd.Activation(x, act_type="relu").asnumpy(), [0, 0, 2])
    np.testing.assert_allclose(nd.relu(x).asnumpy(), [0, 0, 2])
    np.testing.assert_allclose(nd.LeakyReLU(x, act_type="leaky", slope=0.1).asnumpy(),
                               [-0.2, 0, 2], rtol=1e-5)
    np.testing.assert_allclose(nd.sigmoid(x).asnumpy(), 1 / (1 + np.exp([2., 0., -2.])),
                               rtol=1e-5)


def test_softmax():
    x = nd.array([[1.0, 2.0, 3.0]])
    out = nd.softmax(x).asnumpy()
    e = np.exp([1.0, 2.0, 3.0]); e /= e.sum()
    np.testing.assert_allclose(out[0], e, rtol=1e-5)
    np.testing.assert_allclose(nd.log_softmax(x).asnumpy()[0], np.log(e),
                               rtol=1e-4, atol=1e-4)


def test_softmax_output_grad():
    """SoftmaxOutput backward = (p - onehot(y)) — softmax_output.cc semantics."""
    x = nd.array(np.random.rand(4, 5).astype(np.float32))
    y = nd.array([0, 1, 2, 3])
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, y)
    out.backward()
    p = out.asnumpy()
    oh = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    np.testing.assert_allclose(x.grad.asnumpy(), p - oh, rtol=1e-5, atol=1e-6)


def test_embedding():
    w = nd.array(np.random.rand(10, 4).astype(np.float32))
    idx = nd.array([1, 3, 5])
    out = nd.Embedding(idx, w, input_dim=10, output_dim=4)
    np.testing.assert_allclose(out.asnumpy(), w.asnumpy()[[1, 3, 5]])


def test_elemwise_grads():
    for fn in [nd.exp, nd.log, nd.sqrt, nd.tanh, nd.sigmoid]:
        x_np = np.random.rand(3, 3).astype(np.float32) + 0.5
        check_grad(fn, x_np)


def test_broadcast_grad():
    a = nd.array(np.random.rand(3, 1).astype(np.float32))
    b = nd.array(np.random.rand(1, 4).astype(np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), b.asnumpy().sum(1, keepdims=True).repeat(3, 0),
                               rtol=1e-5)


def test_optimizer_update_ops():
    w = nd.array([1.0, 2.0])
    g = nd.array([0.1, 0.1])
    out = nd.sgd_update(w, g, lr=0.1)
    np.testing.assert_allclose(out.asnumpy(), [0.99, 1.99], rtol=1e-6)
    mom = nd.zeros((2,))
    w2, m2 = nd.sgd_mom_update(w, g, mom, lr=0.1, momentum=0.9)
    np.testing.assert_allclose(w2.asnumpy(), [0.99, 1.99], rtol=1e-6)
    mean, var = nd.zeros((2,)), nd.zeros((2,))
    w3, m3, v3 = nd.adam_update(w, g, mean, var, lr=0.01)
    assert w3.shape == (2,)


def test_upsampling():
    x = nd.array(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert out.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(out.asnumpy()[0, 0], np.repeat(np.repeat(
        np.arange(4, dtype=np.float32).reshape(2, 2), 2, 0), 2, 1))


def test_pick_gather():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    idx = nd.array([0, 1])
    np.testing.assert_allclose(nd.pick(x, idx, axis=1).asnumpy(), [1.0, 4.0])
    data = nd.array([[1.0, 2.0], [3.0, 4.0]])
    indices = nd.array([[0, 1], [1, 0]])
    np.testing.assert_allclose(nd.gather_nd(data, indices).asnumpy(), [2.0, 3.0])


def test_slice_ops():
    x = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    out = nd.slice(x, begin=(0, 1), end=(2, 3))
    assert out.shape == (2, 2, 4)
    out = nd.slice_axis(x, axis=2, begin=1, end=3)
    assert out.shape == (2, 3, 2)
    out = nd.slice_like(x, nd.zeros((2, 2, 2)))
    assert out.shape == (2, 2, 2)


def test_eager_jit_cache_not_poisoned_by_trace_mode():
    """Regression (round-3 review): a BatchNorm traced inside a hybridized
    training graph must not leak its train-mode jaxpr into the eager
    predict-mode dispatch cache (and vice versa)."""
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu import autograd as ag

    mx.random.seed(0)
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    net.hybridize()
    x = nd.random.normal(1.0, 2.0, shape=(8, 3, 4, 4))
    with ag.record():
        net(x)  # hybridized training trace (tc.training=True)
    # eager predict-mode BN with the same shapes/attrs must use moving
    # stats (mean 0 var 1 -> output == input)
    out = nd.BatchNorm(x, nd.ones((3,)), nd.zeros((3,)), nd.zeros((3,)),
                       nd.ones((3,)), fix_gamma=False, eps=1e-10)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), rtol=1e-4,
                               atol=1e-4)


def test_op_info_reflection():
    """dmlc::Parameter-style schema reflection (get_op_info/get_op_doc —
    MXSymbolGetAtomicSymbolInfo analog, src/c_api/c_api_symbolic.cc)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.ops import registry

    info = mx.operator.get_op_info("Convolution")
    assert ("data", "NDArray") in info["inputs"]
    assert ("bias", "NDArray, optional") in info["inputs"]
    args = {n: t for n, t, _ in info["arguments"]}
    assert "kernel" in args and "num_filter" in args

    doc = mx.operator.get_op_doc("sgd_mom_update")
    assert "momentum : float, optional, default=0.0" in doc
    # generated wrappers carry the schema docstring
    assert "Parameters:" in mx.nd.sgd_mom_update.__doc__

    # every registered op reflects without error
    for name in mx.operator.get_all_op_names():
        registry.op_info(name)


def test_np_unique_op():
    """_np_unique (src/operator/numpy/np_unique_op.cc) — host-evaluated
    data-dependent-shape op."""
    import numpy as np

    import incubator_mxnet_tpu as mx

    a = mx.nd.array(np.array([3, 1, 2, 3, 1], np.float32))
    np.testing.assert_array_equal(mx.nd._np_unique(a).asnumpy(), [1, 2, 3])
    u, inv, cnt = mx.nd._np_unique(a, return_inverse=True,
                                   return_counts=True)
    np.testing.assert_array_equal(u.asnumpy()[inv.asnumpy()],
                                  a.asnumpy())
    np.testing.assert_array_equal(cnt.asnumpy(), [2, 1, 2])


def test_kl_sparse_reg_backward_via_frontend():
    """ADVICE r3: IdentityAttachKLSparseReg backward through nd/autograd
    (the custom_vjp residuals must survive the eager-jit invoke path)."""
    from incubator_mxnet_tpu import autograd

    x = mx.nd.random.uniform(shape=(4, 6))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.IdentityAttachKLSparseReg(x, sparseness_target=0.1,
                                            penalty=0.001)
        y.sum().backward()
    g = x.grad.asnumpy()
    rho_hat = np.clip(x.asnumpy().mean(0), 1e-6, 1 - 1e-6)
    kl = 0.001 / 4 * (-0.1 / rho_hat + 0.9 / (1 - rho_hat))
    np.testing.assert_allclose(g, 1.0 + np.broadcast_to(kl, g.shape),
                               rtol=1e-5)


def test_hawkesll_gradients_flow():
    """ADVICE r3: hawkesll is a trainable log-likelihood — gradients wrt
    mu/alpha/beta must flow (reference registers a gradient,
    src/operator/contrib/hawkes_ll.cc)."""
    from incubator_mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    mu = mx.nd.array(np.full(3, 0.5, np.float32))
    alpha = mx.nd.array(np.full(3, 0.3, np.float32))
    beta = mx.nd.array(np.full(3, 1.0, np.float32))
    for p in (mu, alpha, beta):
        p.attach_grad()
    lags = mx.nd.array(rng.exponential(1, (2, 5)).astype(np.float32))
    marks = mx.nd.array(rng.randint(0, 3, (2, 5)).astype(np.float32))
    with autograd.record():
        ll, _ = mx.nd.contrib.hawkesll(
            mu, alpha, beta, lags, marks,
            mx.nd.array(np.full(2, 5, np.float32)),
            mx.nd.array(np.full(2, 6.0, np.float32)))
        ll.sum().backward()
    assert np.abs(mu.grad.asnumpy()).sum() > 0
    assert np.abs(alpha.grad.asnumpy()).sum() > 0
    assert np.abs(beta.grad.asnumpy()).sum() > 0


def test_multi_output_compose_metadata():
    """ADVICE r3: symbol composition must report the actual output count
    for _contrib_calibrate_entropy and _npi_average(returned=True)."""
    import incubator_mxnet_tpu.symbol as sym

    h = sym.Variable("h")
    e = sym.Variable("e")
    assert len(sym.contrib.calibrate_entropy(h, e).list_outputs()) == 2
    a = sym.Variable("a")
    av = getattr(sym, "_npi_average")
    assert len(av(a, returned=True).list_outputs()) == 2
    assert len(av(a).list_outputs()) == 1
