"""Round-20 tool wiring.

* ``tools/chip_queue.sh`` CHIP_QUEUE_DRY_RUN=1: the measurement queue
  runs end-to-end on CPU — heavy chip legs print-and-skip, while the
  kernel-variant sweep and the graftsched train-schedule winner legs
  execute tiny interpret-mode workloads and validate their artifact
  contracts.  A flag or JSON drift in the queue fails HERE, in tier-1,
  not mid-chip-window.
* ``bench.py --schedule-config``: the autotune winner loader fails
  fast (before the ResNet build) on a malformed config.
* ``tools/graftcost.py --kernel-plans``: the per-layer fused-BN
  kernel-plan table pins the round-20 selections at the real VMEM
  budget — lane-fold stem, spatial-tiled 56x56 identity exits, whole-L
  everywhere else — and accounts for all 53 BN layers of ResNet-50.
"""
import importlib.util
import json
import os
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_cli(name, path):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chip_queue_dry_run(tmp_path):
    env = dict(os.environ, CHIP_QUEUE_DRY_RUN="1", JAX_PLATFORMS="cpu")
    log = tmp_path / "queue.log"
    r = subprocess.run(
        ["bash", os.path.join(ROOT, "tools", "chip_queue.sh"), str(log)],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=280)
    out = log.read_text() if log.exists() else r.stdout
    assert r.returncode == 0, out[-2000:]
    # the artifact-producing legs actually ran and their contracts held
    assert "kernel-variant sweep contract ok" in out, out[-2000:]
    assert "schedule-winner contract ok" in out, out[-2000:]
    # chip legs were skipped, not silently attempted on CPU
    assert "[dry-run] skip" in out
    assert "== done" in out


def test_bench_schedule_config_rejects_malformed(tmp_path):
    bench = _load_cli("bench_cli", "bench.py")
    bad = tmp_path / "winner.json"
    bad.write_text(json.dumps({"target": "train-schedule", "knobs": {}}))
    # the loader runs BEFORE the ResNet build: a malformed winner config
    # costs an exception, not a model build + trace
    with pytest.raises(ValueError, match="schedule"):
        bench.run_train(schedule_config=str(bad))


def test_graftcost_kernel_plans_table(capsys):
    gc = _load_cli("graftcost_cli", "tools/graftcost.py")
    rc = gc.main(["--model", "resnet50", "--kernel-plans", "--batch",
                  "256", "--compute-dtype", "bfloat16", "--format",
                  "json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["bn_group"] == 16 and payload["itemsize"] == 2
    layers = {r["layer"]: r for r in payload["layers"]}
    # all 53 BN layers accounted: stem + 16 blocks x 3 + 4 shortcuts
    assert sum(r["count"] for r in payload["layers"]) == 53
    stem = layers["stem"]
    assert stem["variant"] == "lanefold" and stem["fold"] == 2
    assert stem["window_mb"] == 25.7  # 51.4 MB whole-L halved
    ex = layers["stage1.exit"]
    assert ex["variant"] == "tiled" and ex["bwd"] == "tiled"
    assert ex["l_tile"] == 1568 and ex["dual"]
    # the 56x56 downsample exit fits whole-L fwd (donated residual) but
    # must tile its backward
    ds = layers["stage1.exit.ds"]
    assert ds["variant"] == "fused" and ds["bwd"] == "tiled"
    # everything from 28x28 down stays whole-L fused
    for name in ("stage2.exit", "stage3.exit", "stage4.exit",
                 "stage4.exit.tail"):
        assert layers[name]["variant"] == "fused", (name, layers[name])
    assert layers["stage4.exit.tail"]["dual"] is False

    rc = gc.main(["--model", "resnet50", "--kernel-plans",
                  "--compute-dtype", "bfloat16", "--batch", "256"])
    assert rc == 0
    table = capsys.readouterr().out
    assert "lanefold" in table and "tiled" in table
