"""Self-healing training supervisor (parallel/supervisor.py,
tools/supervise.py — docs/RESILIENCE.md §7).

The acceptance surface:

- **heartbeat protocol** — atomic per-rank files through the
  checkpoint write choke point (``fail_writes`` interposes; a write
  outage degrades monitoring, never training), torn files invisible;
- **detectors** — hang (auto-calibrated stall timeout), straggler
  (step lag vs the median), divergence (skip streak past budget /
  finite exploding loss EMA) as pure, unit-testable verdicts;
- **policy ladder** — in-process rollback → kill-and-respawn (bounded)
  → elastic shrink → post-mortem give-up, in ORDER, each rung bounded:
  an exhausted budget produces a post-mortem, never a hang;
- **ledger** — every event (gap, verdict, rollback, restart, shrink,
  recovery + MTTR, resolution) in merge-readable JSONL next to the
  checkpoints, torn trailing lines tolerated;
- **end-to-end** — a SIGKILLed single-rank run auto-respawns, restores
  the last committed checkpoint and finishes with losses BIT-identical
  to the uninterrupted reference (the fast leg; the full chaos matrix
  × MTTR bound soak is marked ``slow``).

Budget discipline: the ladder tests drive scripted stub processes
(no subprocesses); exactly one fast leg spawns real workers.
"""
import json
import os
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.io import NDArrayIter, ResilientIter
from incubator_mxnet_tpu.parallel import (CheckpointManager,
                                          DivergenceDetector,
                                          DivergenceError, HealthLedger,
                                          HeartbeatEmitter, Supervisor,
                                          SupervisorConfig,
                                          make_train_step, run_supervised)
from incubator_mxnet_tpu.parallel import fault_injection as fi
from incubator_mxnet_tpu.parallel.supervisor import (EXIT_DIVERGED,
                                                     StepClock,
                                                     committed_steps,
                                                     hang_verdicts,
                                                     read_heartbeats,
                                                     read_ledger,
                                                     straggler_verdicts)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# heartbeat protocol
# ---------------------------------------------------------------------------

def test_heartbeat_roundtrip_and_torn_files_skipped(tmp_path):
    d = str(tmp_path)
    em = HeartbeatEmitter(d, rank=3)
    em.emit(5, loss=1.25, loss_scale=2.0, skipped_steps=1)
    em.emit(6, loss=1.0, loss_scale=2.0, skipped_steps=1)
    hbs = read_heartbeats(d)
    assert list(hbs) == [3]
    hb = hbs[3]
    assert hb["step"] == 6 and hb["seq"] == 2
    assert hb["loss"] == 1.0 and hb["loss_scale"] == 2.0
    assert hb["skipped_steps"] == 1 and hb["status"] == "running"
    assert hb["time"] <= time.time()
    # a torn/garbage heartbeat (crash mid-write on a pre-atomic fs)
    # is skipped, not fatal — and .tmp twins are invisible by name
    with open(os.path.join(d, "heartbeat-r00009.json"), "w") as f:
        f.write('{"rank": 9, "seq":')
    with open(os.path.join(d, "heartbeat-r00004.json.tmp"), "w") as f:
        f.write("{}")
    assert list(read_heartbeats(d)) == [3]


def test_heartbeat_write_failure_degrades_not_raises(tmp_path):
    """Heartbeats ride checkpoint._write_bytes, so fail_writes
    interposes — and a dead monitoring disk must never kill the
    training step that produced the heartbeat."""
    em = HeartbeatEmitter(str(tmp_path), rank=0)
    with fi.fail_writes(at=0, count=99):
        with pytest.warns(UserWarning, match="heartbeat write failed"):
            em.emit(1, loss=0.5)
    assert em.write_failures == 1
    assert read_heartbeats(str(tmp_path)) == {}
    em.emit(2, loss=0.4)  # recovery: the next beat lands
    assert read_heartbeats(str(tmp_path))[0]["step"] == 2


# ---------------------------------------------------------------------------
# health ledger
# ---------------------------------------------------------------------------

def test_ledger_schema_merge_and_torn_tail(tmp_path):
    d = str(tmp_path)
    led = HealthLedger(os.path.join(d, "health.jsonl"))
    led.append("launch", width=2, attempt=0)
    led.append("fault", verdict="hang", ranks=[1])
    rank_led = HealthLedger(os.path.join(d, "health-r00001.jsonl"))
    rank_led.append("rollback", rank=1, to_step=4)
    # schema: every event carries event/seq/time plus its fields
    for e in led.events():
        assert set(e) >= {"event", "seq", "time"}
    assert [e["event"] for e in led.events()] == ["launch", "fault"]
    assert led.events("fault")[0]["verdict"] == "hang"
    # merged view is time-ordered across writer files
    merged = read_ledger(d)
    assert [e["event"] for e in merged] == ["launch", "fault", "rollback"]
    # a torn trailing line (crash mid-append on a pre-atomic fs) is
    # dropped on re-open; intact events survive
    with open(led.path, "a") as f:
        f.write('{"event": "torn')
    led2 = HealthLedger(led.path)
    assert [e["event"] for e in led2.events()] == ["launch", "fault"]
    led2.append("resolved")
    assert [e["event"] for e in read_ledger(d)][-1] == "resolved"


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------

def test_step_clock_calibrates_stall_timeout():
    c = StepClock(alpha=0.5, factor=8.0, floor=2.0, startup_timeout=120.0)
    assert c.stall_timeout() == 120.0  # no data: startup grace
    c.observe(10.0)
    assert c.stall_timeout() == 120.0  # one arrival: still no interval
    c.observe(10.5)
    assert c.ema == pytest.approx(0.5)
    assert c.stall_timeout() == pytest.approx(4.0)  # 8 x 0.5s
    c.observe(10.6)  # faster steps pull the EMA (and the timeout) down
    assert c.stall_timeout() == pytest.approx(max(2.0, 8 * 0.3))
    for t in (10.61, 10.62, 10.63):
        c.observe(t)
    assert c.stall_timeout() == 2.0  # never below the floor


def test_hang_verdicts():
    now = 100.0
    hbs = {0: {"rank": 0, "step": 5, "status": "running", "time": 99.0},
           1: {"rank": 1, "step": 5, "status": "running", "time": 90.0},
           2: {"rank": 2, "step": 8, "status": "done", "time": 80.0}}
    out = hang_verdicts(hbs, now, timeout=5.0)
    assert [v["rank"] for v in out] == [1]
    assert out[0]["age"] == pytest.approx(10.0)
    # the watcher's own arrival clock wins over the payload stamp
    # (cross-host clock skew must not fabricate a hang)
    out = hang_verdicts(hbs, now, timeout=5.0,
                        last_seen={1: 98.0, 0: 50.0})
    assert [v["rank"] for v in out] == [0]


def test_straggler_verdicts():
    mk = lambda s, st="running": {"step": s, "status": st}  # noqa: E731
    # rank 2 is far behind the median and past min_lag
    out = straggler_verdicts({0: mk(12), 1: mk(11), 2: mk(2)},
                             factor=3.0, min_lag=4)
    assert [v["rank"] for v in out] == [2]
    assert out[0]["lag"] == 9 and out[0]["median"] == 11
    # small lag (startup jitter) never flags
    assert straggler_verdicts({0: mk(5), 1: mk(3)}, factor=3.0,
                              min_lag=4) == []
    # a DONE peer still anchors the median, but is never flagged itself
    out = straggler_verdicts({0: mk(10, "done"), 1: mk(2)},
                             factor=3.0, min_lag=4)
    assert [v["rank"] for v in out] == [1]
    # a single live rank has no fleet to lag behind
    assert straggler_verdicts({0: mk(2)}, factor=3.0, min_lag=4) == []


def test_divergence_detector_skip_streak():
    det = DivergenceDetector(skip_streak_budget=3)
    assert det.update(5, 1.0, skipped_steps=0) is None
    assert det.update(5, None, skipped_steps=1) is None  # streak 1
    assert det.update(5, None, skipped_steps=2) is None  # streak 2
    assert det.suspicious  # an active streak defers checkpoints
    assert det.update(5, None, skipped_steps=3) == "skip_streak"
    det.reset()
    assert det.skip_streak == 0 and not det.suspicious
    # an applied step between skips resets the streak (not consecutive)
    det2 = DivergenceDetector(skip_streak_budget=2)
    det2.update(5, 1.0, skipped_steps=1)
    det2.update(6, 1.0, skipped_steps=1)  # progress: streak cleared
    assert det2.update(6, None, skipped_steps=2) is None
    assert det2.update(7, 1.0, skipped_steps=2) is None


def test_divergence_detector_loss_explosion_and_reset():
    det = DivergenceDetector(explosion_factor=1e3, ema_alpha=0.5,
                             patience=2, warmup=2)
    for loss in (1.0, 1.1, 0.9):
        assert det.update(1, loss) is None
    assert not det.suspicious
    # one hot batch is noise, two sustained is a verdict
    assert det.update(2, 1e7) is None
    assert det.suspicious  # hot: boundary saves must defer
    assert det.update(3, 1e7) == "loss_explosion"
    det.reset()
    assert det.update(4, 1.0) is None
    # non-finite losses never feed the EMA (the skip guard owns them)
    det2 = DivergenceDetector(explosion_factor=1e3, warmup=1)
    det2.update(1, 1.0)
    assert det2.update(1, float("nan")) is None
    assert det2.update(2, float("inf")) is None
    assert det2.ema == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# the policy ladder (scripted stub processes — no subprocess cost)
# ---------------------------------------------------------------------------

class _StubProc:
    def __init__(self, rc):
        self._rc = rc

    def poll(self):
        return self._rc

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return self._rc


def _fast_cfg(**kw):
    kw.setdefault("poll_interval", 0.005)
    kw.setdefault("backoff", 0.01)
    kw.setdefault("max_restarts", 1)
    return SupervisorConfig(**kw)


def test_ladder_order_respawn_shrink_postmortem(tmp_path):
    """Ranks that die on every attempt walk the FULL ladder in order —
    restart (budget per width) → shrink → ... → post-mortem at min
    width — and the run returns bounded instead of hanging."""
    launches = []

    def launch(width, attempt):
        launches.append((width, attempt))
        return [_StubProc(1) for _ in range(width)]

    sup = Supervisor(launch, width=4, directory=str(tmp_path),
                     config=_fast_cfg())
    t0 = time.monotonic()
    out = sup.run(timeout=30.0)
    assert time.monotonic() - t0 < 10.0
    assert out["outcome"] == "gave_up" and out["width"] == 1
    assert out["restarts"] > 0 and out["shrinks"] == 2  # 4 -> 2 -> 1
    # widths only ever narrow, and every shrink halves
    widths = [w for w, _ in launches]
    assert widths[0] == 4 and widths[-1] == 1
    assert all(b <= a for a, b in zip(widths, widths[1:]))
    ev = [e["event"] for e in sup.ledger.events()]
    assert ev[0] == "launch" and ev[-1] == "post_mortem"
    assert ev.index("fault") < ev.index("restart") < ev.index("shrink")
    pm = sup.ledger.events("post_mortem")[0]
    assert pm["reason"].startswith("restart budget exhausted")
    assert pm["event_counts"]["restart"] == out["restarts"]


def test_ladder_diverged_exit_code_is_its_own_verdict(tmp_path):
    """A rank exiting EXIT_DIVERGED (in-process rollback exhausted) is
    escalated as a divergence_exhausted fault, not a generic loss."""
    def launch(width, attempt):
        return [_StubProc(EXIT_DIVERGED)]

    sup = Supervisor(launch, width=1, directory=str(tmp_path),
                     config=_fast_cfg(max_restarts=0))
    out = sup.run(timeout=30.0)
    assert out["outcome"] == "gave_up"
    faults = sup.ledger.events("fault")
    assert faults and all(f["verdict"] == "divergence_exhausted"
                          for f in faults)
    assert faults[0]["returncode"] == EXIT_DIVERGED


def test_ladder_hang_detection_via_startup_timeout(tmp_path):
    """Ranks that never heartbeat at all age out of the startup grace
    and form a hang verdict (the wedged-before-first-step case)."""
    def launch(width, attempt):
        return [_StubProc(None)]  # alive forever, never beats

    sup = Supervisor(launch, width=1, directory=str(tmp_path),
                     config=_fast_cfg(max_restarts=0,
                                      startup_timeout=0.2,
                                      min_stall_timeout=0.2))
    t0 = time.monotonic()
    out = sup.run(timeout=30.0)
    assert time.monotonic() - t0 < 10.0
    assert out["outcome"] == "gave_up"
    assert sup.ledger.events("fault")[0]["verdict"] == "hang"


def test_ladder_recovery_records_mttr(tmp_path):
    """A fault followed by a healthy relaunch closes with a
    ``recovered`` event carrying the measured MTTR, then resolves."""
    d = str(tmp_path)

    def launch(width, attempt):
        if attempt == 0:
            return [_StubProc(1)]  # instant loss
        HeartbeatEmitter(d, rank=0).emit(5, loss=0.5, status="running")
        return [_StubProc(0)]

    sup = Supervisor(launch, width=1, directory=d, config=_fast_cfg())
    out = sup.run(timeout=30.0)
    assert out["outcome"] == "resolved"
    assert out["restarts"] == 1 and len(out["mttrs"]) == 1
    rec = sup.ledger.events("recovered")[0]
    assert rec["mode"] == "respawn" and rec["mttr"] >= 0
    ev = [e["event"] for e in sup.ledger.events()]
    assert ev.index("fault") < ev.index("restart") \
        < ev.index("recovered") < ev.index("resolved")


def test_committed_steps_ignores_torn_stages(tmp_path):
    os.makedirs(tmp_path / "step-00000002")
    os.makedirs(tmp_path / ".tmp-step-00000004")
    os.makedirs(tmp_path / "step-garbage")
    assert committed_steps(str(tmp_path)) == [2]


# ---------------------------------------------------------------------------
# the supervised loop (in-process, real train step)
# ---------------------------------------------------------------------------

def _job(tmp, seed=0, **step_kw):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    for _ in range(2):
        net.add(nn.Dense(16, activation="tanh"))
    net.add(nn.Dense(13))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, 16)))
    kw = dict(optimizer="adam", learning_rate=0.01, lint="error")
    kw.update(step_kw)
    step = make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                           **kw)
    rngd = np.random.RandomState(5)
    X = rngd.rand(64, 16).astype(np.float32)
    Y = rngd.randint(0, 4, 64).astype(np.float32)
    np.random.seed(3)
    it = ResilientIter(NDArrayIter(X, Y, batch_size=8, shuffle=True))
    mgr = CheckpointManager(os.path.join(str(tmp), "ckpt"))
    return step, it, mgr


def test_rollback_on_loss_bomb_resumes_bit_identical(tmp_path):
    """The divergence rung end to end: a finite gradient bomb (invisible
    to nonfinite='skip') explodes the loss EMA, the verdict rolls back
    to the last committed checkpoint — data stream included — and the
    replayed tail matches the unbombed reference run bit for bit."""
    cfg = SupervisorConfig(checkpoint_every=2)
    step, it, mgr = _job(tmp_path / "ref")
    ref = run_supervised(step, it, mgr, until_step=10, config=cfg)
    assert ref["rollbacks"] == 0 and ref["final_step"] == 10

    step2, it2, mgr2 = _job(tmp_path / "bomb")
    with fi.loss_bomb(at=4, factor=1e4) as st:
        out = run_supervised(step2, it2, mgr2, until_step=10, config=cfg)
    assert st.fired == 1 and st.params_scaled > 0
    assert out["rollbacks"] == 1 and out["final_step"] == 10
    # the bombed losses are huge but FINITE (skip guard blind), and the
    # post-rollback tail replays the reference bit-exactly
    bombed = [l for l in out["losses"] if l > 100]
    assert bombed and all(np.isfinite(l) for l in bombed)
    assert out["losses"][-6:] == ref["losses"][-6:]
    events = [e["event"] for e in read_ledger(str(mgr2.directory))]
    assert events.index("divergence") < events.index("rollback") \
        < events.index("recovered") < events.index("done")
    div = [e for e in read_ledger(str(mgr2.directory))
           if e["event"] == "divergence"][0]
    assert div["verdict"] == "loss_explosion"
    # no checkpoint was taken while the stream was suspicious: every
    # committed step is a CLEAN one (rollback target never poisoned)
    assert all(s <= 4 or s >= 6 for s in mgr2.steps())
    hb = read_heartbeats(str(mgr2.directory))[0]
    assert hb["status"] == "done" and hb["step"] == 10


def test_skip_streak_verdict_escalates_bounded(tmp_path):
    """A permanently poisoned stream under a STATIC scale (the GL012
    configuration): skips accumulate with no applied progress, the
    skip-streak verdict fires at the declared budget, and with nothing
    committed to roll back to the loop raises DivergenceError — the
    outer supervisor's escalation cue — instead of spinning forever."""
    step, it, mgr = _job(tmp_path, nonfinite="skip", loss_scale=1024.0,
                         skip_streak_budget=4)
    poisoned = fi.NaNInjector(step, at_steps=range(10 ** 6))
    cfg = SupervisorConfig(checkpoint_every=2)
    t0 = time.monotonic()
    with pytest.raises(DivergenceError, match="skip_streak"):
        run_supervised(poisoned, it, mgr, until_step=8, config=cfg)
    assert time.monotonic() - t0 < 60.0
    assert mgr.steps() == []  # nothing clean was ever committed
    events = read_ledger(str(mgr.directory))
    div = [e for e in events if e["event"] == "divergence"][0]
    assert div["verdict"] == "skip_streak" and div["skip_streak"] == 4
    assert any(e["event"] == "rollback_exhausted" for e in events)
    hb = read_heartbeats(str(mgr.directory))[0]
    assert hb["status"] == "diverged"


def test_hang_step_injector_wedges_and_counts(tmp_path):
    """hang_step drives the supervised choke point: the wedged call
    blocks for the injected duration, then the loop continues."""
    cfg = SupervisorConfig(checkpoint_every=None)
    step, it, mgr = _job(tmp_path)
    with fi.hang_step(at=1, duration=0.3, count=2) as st:
        t0 = time.monotonic()
        out = run_supervised(step, it, mgr, until_step=3, config=cfg)
        waited = time.monotonic() - t0
    assert st.hung == 2 and waited >= 0.6
    assert out["final_step"] == 3


def test_gl012_skip_streak_budget_silences_and_enforces(tmp_path):
    """The skip_streak_budget knob declared on the step is picked up by
    the supervised loop as its detector default (and silences GL012 —
    the lint-side gate lives in tests/test_graftlint.py)."""
    step, it, mgr = _job(tmp_path, nonfinite="skip", loss_scale=512.0,
                         skip_streak_budget=2)
    assert step.skip_streak_budget == 2
    poisoned = fi.NaNInjector(step, at_steps=range(10 ** 6))
    with pytest.raises(DivergenceError, match="skip_streak"):
        run_supervised(poisoned, it, mgr, until_step=4,
                       config=SupervisorConfig(checkpoint_every=None))
    div = [e for e in read_ledger(str(mgr.directory))
           if e["event"] == "divergence"][0]
    assert div["skip_streak"] == 2  # the STEP's budget, not the default
    with pytest.raises(ValueError, match="skip_streak_budget"):
        _job(tmp_path, skip_streak_budget=0)


# ---------------------------------------------------------------------------
# end to end: kill -> auto-respawn -> bit-identical resume (fast leg)
# ---------------------------------------------------------------------------

def test_e2e_kill_auto_resume_bit_identical(tmp_path):
    """THE acceptance case, single rank: a SIGKILLed worker is
    respawned by the supervisor, restores the last committed
    checkpoint (mid-epoch data position included), and its final
    attempt's losses equal the uninterrupted in-process reference
    BIT for bit.  Kept to one scenario and one rank for the tier-1
    budget — the full matrix soaks under ``-m slow``."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import supervise
    finally:
        sys.path.pop(0)

    outdir = str(tmp_path / "run")
    os.makedirs(outdir)
    import argparse

    args = argparse.Namespace(
        n=1, steps=8, dir=outdir, checkpoint_every=2, commit_timeout=10.0,
        max_restarts=2, min_stall=2.0, startup_timeout=60.0,
        backoff=0.1, timeout=120.0)
    out = supervise.supervise_once(args,
                                   chaos_spec="kill_process:at=3")
    assert out["outcome"] == "resolved", out
    assert out["restarts"] == 1 and out["final_step"] == 8
    assert out["torn_visible"] == 0
    for ev in ("launch", "fault", "restart", "recovered", "resolved"):
        assert ev in out["events"], (ev, out["events"])
    assert out["mttrs"] and max(out["mttrs"]) < 60.0

    with open(os.path.join(outdir, "result_rank0.json")) as f:
        res = json.load(f)
    assert res["attempt"] == 1 and res["status"] == "done"
    # the respawned attempt restored the step-2 checkpoint and replayed
    # steps 3..8 — exactly the reference's tail, bit for bit
    ref_step, ref_it, ref_mgr = supervise.build_worker_job(
        str(tmp_path / "ref"))[:3]
    ref = run_supervised(ref_step, ref_it, ref_mgr, until_step=8,
                         config=SupervisorConfig(checkpoint_every=2))
    ref_it.close()
    assert res["restored_from"] == 2
    assert res["losses"] == ref["losses"][2:], (res["losses"],
                                                ref["losses"])


@pytest.mark.slow  # ~60 s: every chaos scenario x the MTTR bound
def test_chaos_matrix_soak(tmp_path):
    """The full matrix through the CLI path: kill_process, hang_step,
    straggler_process, host_loss_during_save, loss_bomb — each must
    resolve with its required ledger sequence, a bounded MTTR and zero
    torn checkpoints visible."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import supervise
    finally:
        sys.path.pop(0)
    import argparse

    args = argparse.Namespace(
        n=1, steps=8, dir=str(tmp_path), checkpoint_every=2,
        commit_timeout=10.0, max_restarts=2, min_stall=2.0,
        startup_timeout=60.0, backoff=0.25, timeout=180.0,
        mttr_bound=60.0, sync="allreduce", straggler_factor=3.0,
        straggler_min_lag=4)
    records = [supervise.run_chaos(s, args, "text")
               for s in sorted(supervise.SCENARIOS)]
    bad = [r for r in records if not r["ok"]]
    assert not bad, bad
    assert {r["scenario"] for r in records} == set(supervise.SCENARIOS)
    # the rollback rung resolves loss_bomb with ZERO restarts
    bomb = next(r for r in records if r["scenario"] == "loss_bomb")
    assert bomb["restarts"] == 0


# ---------------------------------------------------------------------------
# sync→async policy ladder (docs/RESILIENCE.md §8) inside run_supervised
# ---------------------------------------------------------------------------

def test_run_supervised_auto_sync_degrades_and_recovers(tmp_path):
    """The fast tier-1 leg of the straggler chaos scenario's
    async-degradation arm: a ``sync="auto"`` step under ``run_supervised``
    sees a lagging phantom peer in the shared heartbeat dir, degrades
    allreduce→async after the policy's hysteresis (a ``sync_degrade``
    ledger event), then recovers once the peer reports done — and the
    run still reaches ``until_step``."""
    step, it, mgr = _job(tmp_path, sync="auto", staleness_bound=4)
    step.sync_policy.recover_after = 3
    cfg = SupervisorConfig(straggler_factor=1.2, straggler_min_lag=2)
    phantom = HeartbeatEmitter(str(mgr.directory), rank=1)
    phantom.emit(0, status="running")  # wedged at step 0
    modes = []

    def on_step(hb):
        modes.append(step.sync_mode)
        if hb["step"] >= 6:
            # the straggler finishes: clean frames from here on
            phantom.emit(hb["step"], status="done")

    out = run_supervised(step, it, mgr, until_step=12, config=cfg,
                         on_step=on_step)
    assert out["final_step"] == 12
    events = read_ledger(str(mgr.directory))
    names = [e["event"] for e in events]
    assert "sync_degrade" in names and "sync_recover" in names
    assert names.index("sync_degrade") < names.index("sync_recover")
    deg = next(e for e in events if e["event"] == "sync_degrade")
    assert deg["mode"] == "async" and deg["stragglers"] == [1]
    # the run END state recovered to the collective rung...
    assert step.sync_mode == "allreduce"
    # ...and BOTH rungs actually ran steps
    assert "async" in modes and "allreduce" in modes
    assert all(np.isfinite(out["losses"]))
