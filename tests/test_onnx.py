"""ONNX export/import roundtrip tests (model:
tests/python-pytest/onnx/ in the reference)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.contrib import onnx as onnx_mx


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    out = mx.sym.softmax(fc2, name="prob")
    rng = np.random.RandomState(0)
    params = {"fc1_weight": nd.array(rng.uniform(-1, 1, (16, 8))),
              "fc1_bias": nd.array(rng.uniform(-1, 1, (16,))),
              "fc2_weight": nd.array(rng.uniform(-1, 1, (4, 16))),
              "fc2_bias": nd.array(rng.uniform(-1, 1, (4,)))}
    return out, params


def test_mlp_roundtrip(tmp_path):
    sym, params = _mlp()
    path = str(tmp_path / "mlp.onnx")
    onnx_mx.export_model(sym, params, [(2, 8)], onnx_file_path=path)

    sym2, arg2, aux2 = onnx_mx.import_model(path)
    rng = np.random.RandomState(1)
    x = nd.array(rng.uniform(-1, 1, (2, 8)).astype(np.float32))

    exe1 = sym.bind(mx.current_context(), {"data": x, **params})
    ref = exe1.forward()[0].asnumpy()
    exe2 = sym2.bind(mx.current_context(), {"data": x, **arg2})
    out = exe2.forward()[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_conv_pool_bn_roundtrip(tmp_path):
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, num_filter=4, kernel=(3, 3),
                              pad=(1, 1), name="conv1")
    bn = mx.sym.BatchNorm(conv, name="bn1")
    act = mx.sym.Activation(bn, act_type="relu", name="relu1")
    pool = mx.sym.Pooling(act, kernel=(2, 2), stride=(2, 2),
                          pool_type="max", name="pool1")
    flat = mx.sym.Flatten(pool, name="flat")
    rng = np.random.RandomState(0)
    params = {
        "conv1_weight": nd.array(
            rng.uniform(-1, 1, (4, 3, 3, 3)).astype(np.float32)),
        "conv1_bias": nd.zeros((4,)),
        "bn1_gamma": nd.ones((4,)),
        "bn1_beta": nd.zeros((4,)),
        "bn1_moving_mean": nd.zeros((4,)),
        "bn1_moving_var": nd.ones((4,)),
    }
    path = str(tmp_path / "conv.onnx")
    onnx_mx.export_model(flat, params, [(1, 3, 8, 8)],
                         onnx_file_path=path)
    sym2, arg2, aux2 = onnx_mx.import_model(path)

    x = nd.array(rng.uniform(-1, 1, (1, 3, 8, 8)).astype(np.float32))
    args1 = {k: v for k, v in params.items() if "moving" not in k}
    auxs1 = {k: v for k, v in params.items() if "moving" in k}
    exe1 = flat.bind(mx.current_context(), {"data": x, **args1},
                     aux_states=auxs1)
    ref = exe1.forward(is_train=False)[0].asnumpy()
    exe2 = sym2.bind(mx.current_context(), {"data": x, **arg2},
                     aux_states=aux2)
    out = exe2.forward(is_train=False)[0].asnumpy()
    # float32 proto roundtrip + BN rsqrt gives ~1e-4 relative noise
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=1e-3)


def test_elemwise_and_reshape_roundtrip(tmp_path):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.reshape(mx.sym.broadcast_add(a, b) * a, shape=(-1,),
                         name="out")
    path = str(tmp_path / "ew.onnx")
    onnx_mx.export_model(out, {}, [(2, 3), (2, 3)], onnx_file_path=path)
    sym2, arg2, _ = onnx_mx.import_model(path)
    rng = np.random.RandomState(0)
    av = nd.array(rng.uniform(size=(2, 3)).astype(np.float32))
    bv = nd.array(rng.uniform(size=(2, 3)).astype(np.float32))
    exe1 = out.bind(mx.current_context(), {"a": av, "b": bv})
    exe2 = sym2.bind(mx.current_context(), {"a": av, "b": bv})
    np.testing.assert_allclose(exe2.forward()[0].asnumpy(),
                               exe1.forward()[0].asnumpy(), rtol=1e-6)


def test_onnx_file_is_wellformed_proto(tmp_path):
    """The emitted bytes parse back with our own decoder and contain the
    expected structure (ir_version, opset, graph nodes)."""
    from incubator_mxnet_tpu.contrib.onnx import _proto as P
    sym, params = _mlp()
    path = str(tmp_path / "wf.onnx")
    onnx_mx.export_model(sym, params, [(2, 8)], onnx_file_path=path)
    model = P.decode_model(open(path, "rb").read())
    ops = [n["op_type"] for n in model["nodes"]]
    assert "Gemm" in ops and "Relu" in ops and "Softmax" in ops
    assert set(model["initializers"]) == set(params)
    assert model["inputs"][0][0] == "data"
