"""Detection contrib op tests vs numpy oracles (model:
tests/python/unittest/test_contrib_operator.py in the reference)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def np_iou(a, b):
    il = max(a[0], b[0]); it = max(a[1], b[1])
    ir = min(a[2], b[2]); ib = min(a[3], b[3])
    iw = max(ir - il, 0); ih = max(ib - it, 0)
    inter = iw * ih
    ua = max(a[2] - a[0], 0) * max(a[3] - a[1], 0)
    ub = max(b[2] - b[0], 0) * max(b[3] - b[1], 0)
    u = ua + ub - inter
    return inter / u if u > 0 else 0.0


def test_multibox_prior_formula():
    H, W = 3, 5
    sizes, ratios = (0.4, 0.8), (1.0, 2.0)
    data = nd.zeros((1, 2, H, W))
    out = nd.contrib.MultiBoxPrior(data, sizes=sizes, ratios=ratios)
    k = len(sizes) + len(ratios) - 1
    assert out.shape == (1, H * W * k, 4)
    a = out.asnumpy().reshape(H, W, k, 4)
    # manual first pixel (r=0,c=0): centers
    cy, cx = 0.5 / H, 0.5 / W
    exp = []
    r0 = np.sqrt(ratios[0])
    for s in sizes:
        w = s * H / W * r0 / 2; h = s / r0 / 2
        exp.append([cx - w, cy - h, cx + w, cy + h])
    rr = np.sqrt(ratios[1])
    w = sizes[0] * H / W * rr / 2; h = sizes[0] / rr / 2
    exp.append([cx - w, cy - h, cx + w, cy + h])
    np.testing.assert_allclose(a[0, 0], np.array(exp), rtol=1e-5, atol=1e-6)


def test_multibox_prior_clip():
    data = nd.zeros((1, 2, 2, 2))
    out = nd.contrib.MultiBoxPrior(data, sizes=(1.5,), clip=True).asnumpy()
    assert out.min() >= 0 and out.max() <= 1


def test_box_iou():
    rng = np.random.RandomState(0)
    a = rng.uniform(0, 1, (4, 4)); a[:, 2:] += a[:, :2]
    b = rng.uniform(0, 1, (3, 4)); b[:, 2:] += b[:, :2]
    out = nd.contrib.box_iou(nd.array(a), nd.array(b)).asnumpy()
    assert out.shape == (4, 3)
    for i in range(4):
        for j in range(3):
            np.testing.assert_allclose(out[i, j], np_iou(a[i], b[j]),
                                       rtol=1e-5, atol=1e-6)


def test_box_nms_basic():
    # rows: [id, score, x1, y1, x2, y2]
    data = np.array([
        [0, 0.9, 0.0, 0.0, 0.5, 0.5],
        [0, 0.8, 0.05, 0.05, 0.5, 0.5],   # overlaps box0 → suppressed
        [1, 0.7, 0.0, 0.0, 0.5, 0.5],     # other class → kept
        [0, 0.6, 0.6, 0.6, 0.9, 0.9],     # far away → kept
        [0, 0.05, 0.6, 0.6, 0.9, 0.9],    # below valid_thresh → invalid
    ], dtype=np.float32)
    out = nd.contrib.box_nms(nd.array(data[None]), overlap_thresh=0.5,
                             valid_thresh=0.1, id_index=0,
                             score_index=1, coord_start=2).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    assert len(kept) == 3
    np.testing.assert_allclose(kept[:, 1], [0.9, 0.7, 0.6], rtol=1e-6)
    # force_suppress removes the other-class duplicate too
    out2 = nd.contrib.box_nms(nd.array(data[None]), overlap_thresh=0.5,
                              valid_thresh=0.1, id_index=0, score_index=1,
                              coord_start=2, force_suppress=True).asnumpy()[0]
    kept2 = out2[out2[:, 0] >= 0]
    assert len(kept2) == 2
    np.testing.assert_allclose(kept2[:, 1], [0.9, 0.6], rtol=1e-6)


def test_multibox_target_simple():
    # 2 anchors, 1 gt that overlaps anchor 0 strongly
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5], [0.5, 0.5, 1.0, 1.0]]],
                       dtype=np.float32)
    label = np.array([[[1.0, 0.05, 0.05, 0.45, 0.45],
                       [-1, -1, -1, -1, -1]]], dtype=np.float32)
    cls_pred = np.zeros((1, 3, 2), dtype=np.float32)
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred))
    cls_t = cls_t.asnumpy()[0]
    loc_m = loc_m.asnumpy()[0].reshape(2, 4)
    loc_t = loc_t.asnumpy()[0].reshape(2, 4)
    assert cls_t[0] == 2.0          # class 1 → target 1+1
    assert cls_t[1] == 0.0          # background (no mining → negative)
    np.testing.assert_allclose(loc_m[0], 1)
    np.testing.assert_allclose(loc_m[1], 0)
    # loc encoding oracle
    aw = ah = 0.5; ax = ay = 0.25
    gx = gy = 0.25; gw = gh = 0.4
    exp = [(gx - ax) / aw / 0.1, (gy - ay) / ah / 0.1,
           np.log(gw / aw) / 0.2, np.log(gh / ah) / 0.2]
    np.testing.assert_allclose(loc_t[0], exp, rtol=1e-4, atol=1e-5)


def test_multibox_target_negative_mining():
    rng = np.random.RandomState(0)
    A = 8
    anchors = rng.uniform(0, 0.4, (1, A, 4)).astype(np.float32)
    anchors[..., 2:] += anchors[..., :2] + 0.1
    # one gt matching anchor 0 exactly
    label = np.full((1, 3, 5), -1.0, dtype=np.float32)
    label[0, 0] = [0.0, *anchors[0, 0]]
    cls_pred = rng.uniform(-1, 1, (1, 4, A)).astype(np.float32)
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred),
        negative_mining_ratio=2.0, negative_mining_thresh=0.5,
        ignore_label=-1)
    cls_t = cls_t.asnumpy()[0]
    n_pos = np.sum(cls_t > 0)
    n_neg = np.sum(cls_t == 0)
    n_ign = np.sum(cls_t == -1)
    assert n_pos >= 1
    assert n_neg <= 2 * n_pos
    assert n_pos + n_neg + n_ign == A


def test_multibox_target_no_gt():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5]]], dtype=np.float32)
    label = np.full((1, 2, 5), -1.0, dtype=np.float32)
    cls_pred = np.zeros((1, 2, 1), dtype=np.float32)
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred))
    assert cls_t.asnumpy()[0, 0] == -1.0
    np.testing.assert_allclose(loc_m.asnumpy(), 0)


def test_multibox_detection_decode_and_nms():
    A = 3
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.11, 0.11, 0.31, 0.31],
                         [0.6, 0.6, 0.9, 0.9]]], dtype=np.float32)
    # cls_prob (N, C, A): background + 1 class
    cls_prob = np.array([[[0.2, 0.3, 0.9],
                          [0.8, 0.7, 0.1]]], dtype=np.float32)
    loc_pred = np.zeros((1, A * 4), dtype=np.float32)
    out = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        nms_threshold=0.5, threshold=0.2).asnumpy()[0]
    kept = out[out[:, 0] >= 0]
    # anchors 0,1 are near-duplicates of class 0 → one survives; anchor 2 is
    # background (score 0.1 < threshold)
    assert len(kept) == 1
    assert kept[0, 0] == 0.0
    np.testing.assert_allclose(kept[0, 1], 0.8, rtol=1e-6)
    # zero loc_pred → decoded box == anchor box
    np.testing.assert_allclose(kept[0, 2:], anchors[0, 0], rtol=1e-5,
                               atol=1e-6)


def test_bipartite_matching():
    dist = np.array([[[0.9, 0.1], [0.8, 0.7], [0.2, 0.3]]], dtype=np.float32)
    rows, cols = nd.contrib.bipartite_matching(nd.array(dist))
    rows = rows.asnumpy()[0]; cols = cols.asnumpy()[0]
    # greedy: (0,0)=0.9 then (1,1)=0.7
    np.testing.assert_allclose(rows, [0, 1, -1])
    np.testing.assert_allclose(cols, [0, 1])


def test_roi_pooling_vs_oracle():
    data = np.arange(2 * 1 * 6 * 6, dtype=np.float32).reshape(2, 1, 6, 6)
    rois = np.array([[0, 0, 0, 5, 5], [1, 2, 2, 5, 5]], dtype=np.float32)
    out = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
    assert out.shape == (2, 1, 2, 2)
    # roi 0 covers whole 6x6 → bins are 3x3 max pools
    img = data[0, 0]
    exp = np.array([[img[:3, :3].max(), img[:3, 3:].max()],
                    [img[3:, :3].max(), img[3:, 3:].max()]])
    np.testing.assert_allclose(out[0, 0], exp)
    # roi 1 on image 1: rows/cols 2..5
    img1 = data[1, 0, 2:6, 2:6]
    exp1 = np.array([[img1[:2, :2].max(), img1[:2, 2:].max()],
                     [img1[2:, :2].max(), img1[2:, 2:].max()]])
    np.testing.assert_allclose(out[1, 0], exp1)


def test_roi_align_runs_and_grads():
    rng = np.random.RandomState(0)
    data = nd.array(rng.uniform(size=(1, 2, 8, 8)).astype(np.float32))
    rois = nd.array(np.array([[0, 1, 1, 6, 6]], dtype=np.float32))
    data.attach_grad()
    with mx.autograd.record():
        out = nd.contrib.ROIAlign(data, rois, pooled_size=(3, 3),
                                  spatial_scale=1.0, sample_ratio=2)
        loss = out.sum()
    loss.backward()
    assert out.shape == (1, 2, 3, 3)
    g = data.grad.asnumpy()
    assert np.abs(g).sum() > 0  # gradients flow to sampled region


def test_contrib_symbol_path():
    """MultiBox ops compose symbolically (SSD symbol_builder pattern)."""
    data = mx.sym.Variable("data")
    anchors = mx.sym.contrib.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,))
    _, out_shapes, _ = anchors.infer_shape(data=(1, 3, 4, 4))
    assert tuple(out_shapes[0]) == (1, 16, 4)
