"""Pin jax to N virtual XLA-CPU devices — the single copy of the
"never dial the shared TPU tunnel" recipe used by tests/conftest.py and
__graft_entry__.dryrun_multichip.

Import-light: importing this module does not import jax; ``pin_cpu`` sets
env vars first and only then imports jax, so it works as long as no jax
backend has been initialized yet in the process.
"""
import os
import re


def pin_cpu(n_devices: int = 8):
    """Force cpu-only jax with >= n_devices virtual host devices."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % n_devices
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), "--xla_force_host_platform_device_count=%d" % n_devices)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    # sitecustomize may have stamped jax_platforms="axon,..." already;
    # re-pin cpu-only (effective while no backend is initialized).
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge

    live = set(getattr(xla_bridge, "_backends", None) or ())
    if live - {"cpu"}:
        import warnings

        warnings.warn(
            "pin_cpu called after a non-cpu jax backend was already "
            "initialized (%r) — the cpu pin may be ineffective"
            % sorted(live), stacklevel=2)
    return jax
