#!/usr/bin/env python
"""Graded config 5: SSD detection training (reference: example/ssd/train.py
→ train/train_net.py:239-264 — MultiBoxPrior/Target/Detection contrib ops,
NMS, detection-shaped data, MApMetric-style evaluation).

A compact SSD over a tiny conv backbone on synthetic detection data: the
point is exercising the reference's multibox training loop end to end —
prior generation, target matching, joint cls+loc loss, and NMS decoding.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.ops import registry as reg


class TinySSD(gluon.HybridBlock):
    """One-scale SSD head (symbol_builder.py:90 shape, miniaturized)."""

    def __init__(self, num_classes=3, num_anchors=3, **kw):  # 3 = len(sizes)+len(ratios)-1
        super().__init__(**kw)
        self._nc = num_classes
        self._na = num_anchors
        with self.name_scope():
            self.features = nn.HybridSequential()
            self.features.add(nn.Conv2D(16, 3, padding=1, strides=2),
                              nn.Activation("relu"),
                              nn.Conv2D(32, 3, padding=1, strides=2),
                              nn.Activation("relu"))
            self.cls_head = nn.Conv2D(num_anchors * (num_classes + 1), 3,
                                      padding=1)
            self.loc_head = nn.Conv2D(num_anchors * 4, 3, padding=1)

    def hybrid_forward(self, F, x):  # noqa: N803
        feat = self.features(x)
        cls = self.cls_head(feat)
        loc = self.loc_head(feat)
        return feat, cls, loc


def synthetic_batch(rng, batch, size=32):
    """Images with one colored square; label = (cls, x1, y1, x2, y2)."""
    imgs = rng.rand(batch, 3, size, size).astype(np.float32) * 0.1
    labels = np.full((batch, 1, 5), -1.0, np.float32)
    for i in range(batch):
        cls = rng.randint(0, 3)
        w = rng.randint(8, 16)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - w)
        imgs[i, cls, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + w) / size]
    return imgs, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--batches", type=int, default=60)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = TinySSD()
    net.initialize(init=mx.init.Xavier())
    net.shape_init((1, 3, 32, 32))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()

    for b in range(args.batches):
        imgs, labels = synthetic_batch(rng, args.batch_size)
        x = nd.array(imgs)
        y = nd.array(labels)
        with autograd.record():
            feat, cls_pred, loc_pred = net(x)
            # priors over the feature map (MultiBoxPrior)
            anchors = reg.invoke(
                "_contrib_MultiBoxPrior", [feat],
                sizes=(0.3, 0.5), ratios=(1.0, 2.0))
            n_anchor = anchors.shape[1]
            # reshape heads to (N, A, C+1) / (N, A*4)
            cp = cls_pred.transpose((0, 2, 3, 1)).reshape(
                (args.batch_size, n_anchor, 4))
            cp = cp.transpose((0, 2, 1))  # (N, C+1, A) for MultiBoxTarget
            lp = loc_pred.transpose((0, 2, 3, 1)).reshape(
                (args.batch_size, -1))
            with autograd.pause():
                loc_t, loc_mask, cls_t = reg.invoke(
                    "_contrib_MultiBoxTarget", [anchors, y, cp])
            cls_l = cls_loss(cp.transpose((0, 2, 1)).reshape((-1, 4)),
                             cls_t.reshape((-1,)))
            loc_l = ((lp - loc_t).abs() * loc_mask).mean()
            loss = cls_l.mean() + loc_l
        loss.backward()
        trainer.step(args.batch_size)
        if (b + 1) % 20 == 0 or (b + 1) == args.batches:
            logging.info("batch %d  loss %.4f", b + 1,
                         float(loss.asscalar()))

    # decode with NMS (MultiBoxDetection) on one batch
    imgs, _ = synthetic_batch(rng, args.batch_size)
    feat, cls_pred, loc_pred = net(nd.array(imgs))
    anchors = reg.invoke("_contrib_MultiBoxPrior", [feat],
                         sizes=(0.3, 0.5), ratios=(1.0, 2.0))
    n_anchor = anchors.shape[1]
    cp = cls_pred.transpose((0, 2, 3, 1)).reshape(
        (args.batch_size, n_anchor, 4)).transpose((0, 2, 1))
    cls_prob = reg.invoke("softmax", [cp], axis=1)
    lp = loc_pred.transpose((0, 2, 3, 1)).reshape((args.batch_size, -1))
    dets = reg.invoke("_contrib_MultiBoxDetection",
                      [cls_prob, lp, anchors], nms_threshold=0.5)
    logging.info("detections shape: %s (id/score/4 coords per anchor)",
                 dets.shape)


if __name__ == "__main__":
    main()
