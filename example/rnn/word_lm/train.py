#!/usr/bin/env python
"""Graded config 4: LSTM language model (reference:
example/rnn/word_lm/train.py:96 — fused RNN op, stateful module-style
state threading, truncated BPTT) plus a bucketing variant
(example/rnn/bucketing/lstm_bucketing.py — BucketSentenceIter +
BucketingModule).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon import nn, rnn


class WordLM(gluon.HybridBlock):
    """Embedding -> fused LSTM -> tied softmax head (model.py:34 analog —
    the cuDNN FusedRNNCell becomes the scan-based fused RNN layer)."""

    def __init__(self, vocab, embed=64, hidden=128, layers=2, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embedding = nn.Embedding(vocab, embed)
            self.lstm = rnn.LSTM(hidden, num_layers=layers,
                                 layout="NTC")
            self.decoder = nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, x, state0=None, state1=None):  # noqa: N803
        emb = self.embedding(x)
        out = self.lstm(emb)
        return self.decoder(out)


def synthetic_corpus(vocab, n_tokens, seed=0):
    """Markov-ish synthetic token stream (learnable structure)."""
    rng = np.random.RandomState(seed)
    toks = [0]
    for _ in range(n_tokens - 1):
        toks.append((toks[-1] * 7 + rng.randint(0, 3)) % vocab)
    return np.asarray(toks, np.int64)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--bptt", type=int, default=20)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1.0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    corpus = synthetic_corpus(args.vocab, args.batch_size * args.bptt * 20)
    n = len(corpus) // args.batch_size * args.batch_size
    data = corpus[:n].reshape(args.batch_size, -1)

    mx.random.seed(0)
    net = WordLM(args.vocab)
    net.initialize(init=mx.init.Xavier())
    net.shape_init((args.batch_size, args.bptt), dtype="int64")
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    T = data.shape[1]
    for epoch in range(args.epochs):
        total, nb = 0.0, 0
        for lo in range(0, T - args.bptt - 1, args.bptt):
            x = nd.array(data[:, lo:lo + args.bptt].astype(np.float32))
            y = nd.array(
                data[:, lo + 1:lo + args.bptt + 1].astype(np.float32))
            with autograd.record():
                logits = net(x)
                loss = loss_fn(logits.reshape((-1, args.vocab)),
                               y.reshape((-1,))).mean()
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.asscalar())
            nb += 1
        ppl = float(np.exp(total / nb))
        logging.info("epoch %d  loss %.3f  ppl %.1f", epoch, total / nb, ppl)


if __name__ == "__main__":
    main()
