#!/usr/bin/env python
"""Long-context language model with ring-attention sequence parallelism.

Trains a small decoder-only transformer whose attention runs as ONE
compiled SPMD program with q/k/v sharded over the sequence dimension
(``parallel.ring_attention``) — the long-context capability SURVEY §5.7
makes first-class (the reference has no analog; its transformer example
is single-device ``_contrib_interleaved_matmul_selfatt_*``).

Run on the virtual mesh (no TPU needed):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python example/long_context/train_lm.py --seq 512 --devices 8

On a TPU pod slice, drop the env overrides; the same script scales the
``sp`` axis over the real chips and the collectives ride ICI.
"""
import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import numpy as np


def build_params(rng, vocab, dim, n_layers, ffn_mult=4):
    import jax.numpy as jnp

    def lin(i, o):
        return jnp.asarray(rng.normal(0, (2.0 / (i + o)) ** 0.5,
                                      (i, o)).astype(np.float32))

    params = {"embed": jnp.asarray(
        rng.normal(0, 0.02, (vocab, dim)).astype(np.float32))}
    for li in range(n_layers):
        params["l%d" % li] = {
            "ln1_g": jnp.ones(dim, jnp.float32),
            "ln1_b": jnp.zeros(dim, jnp.float32),
            "wq": lin(dim, dim), "wk": lin(dim, dim), "wv": lin(dim, dim),
            "wo": lin(dim, dim),
            "ln2_g": jnp.ones(dim, jnp.float32),
            "ln2_b": jnp.zeros(dim, jnp.float32),
            "w1": lin(dim, dim * ffn_mult), "w2": lin(dim * ffn_mult, dim),
        }
    params["out"] = lin(dim, vocab)
    return params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--devices", type=int, default=0,
                    help="sp axis size (0 = all devices)")
    ap.add_argument("--impl", default="ring", choices=["ring", "ulysses"])
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from incubator_mxnet_tpu.parallel import make_mesh
    from incubator_mxnet_tpu.parallel.ring_attention import (
        sharded_self_attention)

    ndev = args.devices or len(jax.devices())
    mesh = make_mesh({"sp": ndev}, devices=jax.devices()[:ndev])
    print("mesh: sp=%d (%s)" % (ndev, jax.devices()[0].platform))
    H, D = args.heads, args.dim // args.heads

    def ln(x, g, b):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + 1e-5) * g + b

    def forward(params, tokens):
        x = params["embed"][tokens]                    # (B, S, dim)
        B, S, dim = x.shape
        for li in range(args.layers):
            p = params["l%d" % li]
            h = ln(x, p["ln1_g"], p["ln1_b"])
            q = (h @ p["wq"]).reshape(B, S, H, D).transpose(0, 2, 1, 3)
            k = (h @ p["wk"]).reshape(B, S, H, D).transpose(0, 2, 1, 3)
            v = (h @ p["wv"]).reshape(B, S, H, D).transpose(0, 2, 1, 3)
            # sequence-parallel causal attention: q/k/v sharded on dim 2
            att = sharded_self_attention(q, k, v, mesh, seq_axis="sp",
                                         causal=True, impl=args.impl)
            att = att.transpose(0, 2, 1, 3).reshape(B, S, dim)
            x = x + att @ p["wo"]
            h = ln(x, p["ln2_g"], p["ln2_b"])
            x = x + jax.nn.gelu(h @ p["w1"]) @ p["w2"]
        return x @ params["out"]

    def loss_fn(params, tokens):
        logits = forward(params, tokens[:, :-1])
        tgt = tokens[:, 1:]
        lp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(lp, tgt[..., None], -1).mean()

    @jax.jit
    def step(params, opt_m, tokens, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        opt_m = jax.tree.map(lambda m, g: 0.9 * m + g, opt_m, grads)
        params = jax.tree.map(lambda p, m: p - lr * m, params, opt_m)
        return params, opt_m, loss

    rng = np.random.RandomState(0)
    params = build_params(rng, args.vocab, args.dim, args.layers)
    opt_m = jax.tree.map(jnp.zeros_like, params)
    # learnable synthetic task: next token = (token * 2 + 1) mod vocab
    base = rng.randint(0, args.vocab, (args.batch, 1))
    seq = [base]
    for _ in range(args.seq):
        seq.append((seq[-1] * 2 + 1) % args.vocab)
    tokens = jnp.asarray(np.concatenate(seq, axis=1))

    first = last = None
    for i in range(args.steps):
        t0 = time.time()
        params, opt_m, loss = step(params, opt_m, tokens, 0.05)
        loss = float(loss)
        if first is None:
            first = loss
        last = loss
        print("step %2d  loss %.4f  (%.2fs)" % (i, loss, time.time() - t0))
    assert last < first, (first, last)
    print("PASS: loss %.4f -> %.4f over seq %d on sp=%d (%s attention)"
          % (first, last, args.seq, ndev, args.impl))


if __name__ == "__main__":
    main()
