#!/usr/bin/env python
"""Graded config 3: distributed data-parallel training with
``kv.create('dist_sync_device')`` (reference:
example/distributed_training/cifar10_dist.py — dist kvstore, per-worker
data sharding via SplitSampler, Trainer with a store).

Launch:  python tools/launch.py -n 2 python example/distributed_training/cifar10_dist.py
Each worker trains on its shard; gradient sync keeps replicas bitwise
identical (dist_sync semantics over jax.distributed collectives).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, kv, nd
from incubator_mxnet_tpu.gluon import nn


def build_net(classes=10):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(16, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.MaxPool2D(2),
            nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.GlobalAvgPool2D(), nn.Dense(classes))
    return net


def shard(arr, rank, num):
    """SplitSampler semantics: contiguous per-worker shard."""
    per = len(arr) // num
    return arr[rank * per:(rank + 1) * per]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--samples", type=int, default=512)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    store = kv.create("dist_sync_device")
    rank, nworker = store.rank, store.num_workers

    # synthetic CIFAR-shaped data, sharded per worker
    rng = np.random.RandomState(42)  # same dataset everywhere
    X = rng.rand(args.samples, 3, 32, 32).astype(np.float32)
    Y = rng.randint(0, 10, args.samples).astype(np.float32)
    Xs = shard(X, rank, nworker)
    Ys = shard(Y, rank, nworker)

    mx.random.seed(0)  # identical init on every worker
    net = build_net()
    net.initialize(init=mx.init.Xavier())
    net.shape_init((1, 3, 32, 32))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=store)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    bs = args.batch_size
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(Xs))
        total = 0.0
        nb = 0
        for lo in range(0, len(Xs) - bs + 1, bs):
            idx = perm[lo:lo + bs]
            x, y = nd.array(Xs[idx]), nd.array(Ys[idx])
            with autograd.record():
                loss = loss_fn(net(x), y).mean()
            loss.backward()
            trainer.step(bs)
            total += float(loss.asscalar())
            nb += 1
        logging.info("[rank %d/%d] epoch %d mean loss %.4f", rank, nworker,
                     epoch, total / max(nb, 1))
    store.barrier()


if __name__ == "__main__":
    main()
