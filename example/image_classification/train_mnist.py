#!/usr/bin/env python
"""Graded config 1: LeNet/MLP on MNIST through the Module API
(reference: example/image-classification/train_mnist.py:99 +
common/fit.py:150 — symbolic compose, MNISTIter/NDArrayIter, Module.fit,
SoftmaxOutput, SGD, kvstore).

Runs on real MNIST idx files when --data-dir has them, otherwise on a
synthetic stand-in so the script is runnable anywhere.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io as mio
from incubator_mxnet_tpu import symbol as sym


def mlp_symbol(num_classes=10):
    # example/image-classification/symbols/mlp.py structure
    data = sym.var("data")
    h = sym.FullyConnected(data, sym.var("fc1_weight"), sym.var("fc1_bias"),
                           num_hidden=128)
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, sym.var("fc2_weight"), sym.var("fc2_bias"),
                           num_hidden=64)
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, sym.var("fc3_weight"), sym.var("fc3_bias"),
                           num_hidden=num_classes)
    return sym.SoftmaxOutput(h, sym.var("softmax_label"), name="softmax")


def lenet_symbol(num_classes=10):
    # example/image-classification/symbols/lenet.py structure
    data = sym.var("data")
    c1 = sym.Convolution(data, sym.var("c1_weight"), sym.var("c1_bias"),
                         kernel=(5, 5), num_filter=20)
    a1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    c2 = sym.Convolution(p1, sym.var("c2_weight"), sym.var("c2_bias"),
                         kernel=(5, 5), num_filter=50)
    a2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(a2, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f = sym.Flatten(p2)
    h = sym.FullyConnected(f, sym.var("fc1_weight"), sym.var("fc1_bias"),
                           num_hidden=500)
    h = sym.Activation(h, act_type="tanh")
    h = sym.FullyConnected(h, sym.var("fc2_weight"), sym.var("fc2_bias"),
                           num_hidden=num_classes)
    return sym.SoftmaxOutput(h, sym.var("softmax_label"), name="softmax")


def get_iters(args, flat):
    imgs = os.path.join(args.data_dir, "train-images-idx3-ubyte.gz")
    labs = os.path.join(args.data_dir, "train-labels-idx1-ubyte.gz")
    if os.path.exists(imgs):
        train = mio.MNISTIter(image=imgs, label=labs,
                              batch_size=args.batch_size, flat=flat)
        return train, None
    logging.warning("no MNIST files in %s — synthetic data", args.data_dir)
    rng = np.random.RandomState(0)
    n = 2048
    x = rng.rand(n, 784).astype(np.float32) if flat else \
        rng.rand(n, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.float32)
    split = n - 512
    return (mio.NDArrayIter(x[:split], y[:split], args.batch_size,
                            shuffle=True),
            mio.NDArrayIter(x[split:], y[split:], args.batch_size))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    ap.add_argument("--data-dir", default="data/mnist")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = mlp_symbol() if args.network == "mlp" else lenet_symbol()
    train, val = get_iters(args, flat=args.network == "mlp")
    kv = mx.kv.create(args.kv_store)
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            kvstore=kv, eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))


if __name__ == "__main__":
    main()
