#!/usr/bin/env python
"""Graded config 2 + north-star entry: ResNet-50 ImageNet training
(reference: example/image-classification/train_imagenet.py via
example/gluon/image_classification.py subsystems — model_zoo resnet,
fused train step, ImageRecordIter, kvstore dist_sync_device).

The training step is ONE compiled XLA program (fwd+bwd+SGD update, bf16
compute) — `--kv-store dist_sync_device` shards the batch over every
device of a mesh and GSPMD inserts the gradient all-reduce over ICI.
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-train", default="", help=".rec file (synthetic "
                    "batches when empty)")
    ap.add_argument("--data-train-idx", default="")
    ap.add_argument("--network", default="resnet50_v1")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--num-batches", type=int, default=50)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="device",
                    choices=["local", "device", "dist_sync_device"])
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel import make_mesh, make_train_step

    c, h, w = (int(s) for s in args.image_shape.split(","))
    mx.random.seed(0)
    net = getattr(vision, args.network)(classes=args.num_classes)
    net.initialize(init=mx.init.Xavier())
    net.shape_init((1, c, h, w))

    mesh = None
    if args.kv_store == "dist_sync_device":
        devs = jax.devices()
        mesh = make_mesh({"dp": len(devs)}, devices=devs)
        logging.info("dp mesh over %d devices", len(devs))

    step = make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                           optimizer="sgd", learning_rate=args.lr,
                           momentum=0.9, wd=1e-4,
                           compute_dtype=args.dtype, mesh=mesh)

    if args.data_train:
        from incubator_mxnet_tpu.io import ImageRecordIter

        it = ImageRecordIter(
            path_imgrec=args.data_train,
            path_imgidx=args.data_train_idx or None,
            data_shape=(c, h, w), batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True, preprocess_threads=8,
            prefetch_buffer=8)

        def batches():
            while True:
                try:
                    b = next(it)
                except StopIteration:
                    it.reset()
                    b = next(it)
                yield b.data[0], b.label[0]
    else:
        logging.info("synthetic resident batch (pipeline bypass)")
        rng = np.random.RandomState(0)
        x = nd.array(rng.rand(args.batch_size, c, h, w).astype(np.float32))
        y = nd.array(rng.randint(0, args.num_classes,
                                 args.batch_size).astype(np.float32))

        def batches():
            while True:
                yield x, y

    src = batches()
    t0 = time.time()
    for i, (bx, by) in enumerate(src):
        loss = step(bx, by)
        if (i + 1) % 10 == 0:
            loss.wait_to_read()
            dt = time.time() - t0
            logging.info("batch %d  loss %.3f  %.1f img/s", i + 1,
                         float(loss.asscalar()),
                         10 * args.batch_size / dt)
            t0 = time.time()
        if i + 1 >= args.num_batches:
            break


if __name__ == "__main__":
    main()
