#!/usr/bin/env python
"""Gluon imperative/hybrid training loop (reference:
example/gluon/image_classification.py:195-228 — model_zoo network,
hybridize→CachedOp, autograd.record, Trainer + kvstore device).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon.model_zoo import vision
from incubator_mxnet_tpu.io import NDArrayIter


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--samples", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--no-hybridize", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    mx.random.seed(0)
    net = getattr(vision, args.model)(classes=args.num_classes)
    net.initialize(init=mx.init.Xavier())
    net.shape_init((1, 3, args.image_size, args.image_size))
    if not args.no_hybridize:
        net.hybridize()

    rng = np.random.RandomState(0)
    X = rng.rand(args.samples, 3, args.image_size,
                 args.image_size).astype(np.float32)
    Y = rng.randint(0, args.num_classes, args.samples).astype(np.float32)
    it = NDArrayIter(X, Y, args.batch_size, shuffle=True)

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=mx.kv.create("device"))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        it.reset()
        metric.reset()
        total, nb = 0.0, 0
        for batch in it:
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y).mean()
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([y], [out])
            total += float(loss.asscalar())
            nb += 1
        logging.info("epoch %d  loss %.4f  %s", epoch, total / nb,
                     metric.get())


if __name__ == "__main__":
    main()
