#!/usr/bin/env python
"""Per-operator micro-benchmark harness (``benchmark/opperf`` parity).

Reference: ``benchmark/opperf/`` — runs individual operators over
representative shapes and reports per-op latency.  Here each op executes
through the eager dispatch path (per-op compiled executable, warm cache),
so the numbers measure exactly what imperative user code sees.

Usage:
  python benchmark/opperf.py                      # default op set
  python benchmark/opperf.py --ops dot,relu,sum   # subset
  python benchmark/opperf.py --json results.json  # machine-readable dump
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def default_cases():
    r = np.random.RandomState(0)

    def f(*shape):
        return r.normal(0, 1, shape).astype(np.float32)

    b = 32
    return [
        # (op, inputs, attrs)
        ("broadcast_add", [f(b, 256), f(b, 256)], {}),
        ("broadcast_mul", [f(b, 256), f(b, 256)], {}),
        ("relu", [f(b, 1024)], {}),
        ("sigmoid", [f(b, 1024)], {}),
        ("tanh", [f(b, 1024)], {}),
        ("exp", [f(b, 1024)], {}),
        ("sum", [f(b, 64, 64)], {"axis": (1, 2)}),
        ("mean", [f(b, 64, 64)], {"axis": 1}),
        ("softmax", [f(b, 1000)], {}),
        ("log_softmax", [f(b, 1000)], {}),
        ("dot", [f(256, 256), f(256, 256)], {}),
        ("batch_dot", [f(b, 64, 64), f(b, 64, 64)], {}),
        ("FullyConnected", [f(b, 512), f(256, 512), f(256)],
         {"num_hidden": 256}),
        ("Convolution", [f(8, 32, 28, 28), f(64, 32, 3, 3), f(64)],
         {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1)}),
        ("Pooling", [f(8, 32, 28, 28)],
         {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
        ("BatchNorm", [f(8, 32, 28, 28), np.abs(f(32)) + .5, f(32), f(32),
                       np.abs(f(32)) + .5], {"fix_gamma": False}),
        ("LayerNorm", [f(b, 512), np.abs(f(512)) + .5, f(512)], {}),
        ("transpose", [f(b, 64, 64)], {"axes": (2, 0, 1)}),
        ("take", [f(1000, 64), r.randint(0, 1000, 128).astype(np.float32)],
         {}),
        ("topk", [f(b, 1000)], {"k": 10, "ret_typ": "value"}),
        ("sort", [f(b, 1024)], {}),
        ("argmax", [f(b, 1000)], {"axis": 1}),
        ("one_hot", [r.randint(0, 100, b).astype(np.float32)],
         {"depth": 100}),
        ("where", [(f(b, 256) > 0).astype(np.float32), f(b, 256),
                   f(b, 256)], {}),
        ("_contrib_interleaved_matmul_selfatt_qk", [f(128, 4, 192)],
         {"heads": 4}),
    ]


def resnet_cases(batch=64):
    """The hot ResNet-50 ops at representative stage shapes, in bfloat16
    — the dtype the headline bench actually computes in (2x fewer HBM
    bytes and the native MXU path; f32 numbers here would be evidence
    about the wrong configuration).  Per-op TPU latency evidence between
    macro-bench rounds (VERDICT r4 item 8; reference benchmark/opperf/
    runs the same op/shape matrix)."""
    import ml_dtypes

    r = np.random.RandomState(0)

    def f(*shape):
        return r.normal(0, 1, shape).astype(ml_dtypes.bfloat16)

    def conv(n, cin, cout, hw, k, s=1):
        pad = (k // 2, k // 2)
        return ("Convolution",
                [f(n, cin, hw, hw), f(cout, cin, k, k), f(cout)],
                {"kernel": (k, k), "num_filter": cout, "pad": pad,
                 "stride": (s, s)})

    b = batch
    return [
        conv(b, 3, 64, 224, 7, 2),      # stem
        conv(b, 64, 64, 56, 3),         # stage2 3x3
        conv(b, 64, 256, 56, 1),        # stage2 expand
        conv(b, 128, 128, 28, 3),       # stage3 3x3
        conv(b, 256, 512, 28, 1, 2),    # stage3 downsample
        conv(b, 256, 256, 14, 3),       # stage4 3x3
        conv(b, 512, 512, 7, 3),        # stage5 3x3
        ("BatchNorm", [f(b, 256, 56, 56), np.abs(f(256)) + .5, f(256),
                       f(256), np.abs(f(256)) + .5], {"fix_gamma": False}),
        ("BatchNorm", [f(b, 512, 28, 28), np.abs(f(512)) + .5, f(512),
                       f(512), np.abs(f(512)) + .5], {"fix_gamma": False}),
        ("Activation", [f(b, 256, 56, 56)], {"act_type": "relu"}),
        ("elemwise_add", [f(b, 256, 56, 56), f(b, 256, 56, 56)], {}),
        ("Pooling", [f(b, 64, 112, 112)],
         {"kernel": (3, 3), "stride": (2, 2), "pad": (1, 1),
          "pool_type": "max"}),
        ("Pooling", [f(b, 2048, 7, 7)],
         {"global_pool": True, "pool_type": "avg"}),
        ("FullyConnected", [f(b, 2048), f(1000, 2048), f(1000)],
         {"num_hidden": 1000}),
        ("softmax", [f(b, 1000)], {}),
    ]


def bench_op(name, arrays, attrs, warmup=3, iters=50):
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.ops import registry as reg

    ins = [nd.array(a) for a in arrays]
    for _ in range(warmup):
        out = reg.invoke(name, ins, **attrs)
    _wait(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = reg.invoke(name, ins, **attrs)
    _wait(out)
    return (time.perf_counter() - t0) / iters * 1e6  # us


def _wait(out):
    (out[0] if isinstance(out, list) else out).wait_to_read()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default="", help="comma-separated subset")
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--json", default="", help="write results to file")
    ap.add_argument("--resnet", action="store_true",
                    help="hot ResNet-50 ops at stage shapes")
    ap.add_argument("--batch", type=int, default=64,
                    help="batch for --resnet cases")
    args = ap.parse_args()

    cases = (resnet_cases(args.batch) if args.resnet else default_cases())
    if args.ops:
        wanted = set(args.ops.split(","))
        cases = [c for c in cases if c[0] in wanted]

    results = []
    print("%-45s %12s" % ("op", "latency(us)"))
    print("-" * 58)
    for name, arrays, attrs in cases:
        try:
            us = bench_op(name, arrays, attrs, iters=args.iters)
            results.append({"op": name, "latency_us": round(us, 1),
                            "attrs": {k: str(v) for k, v in attrs.items()}})
            print("%-45s %12.1f" % (name, us))
        except Exception as e:  # noqa: BLE001
            results.append({"op": name, "error": str(e)})
            print("%-45s %12s  (%s)" % (name, "ERROR", e))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
        print("wrote %s" % args.json)


if __name__ == "__main__":
    main()
