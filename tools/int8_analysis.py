#!/usr/bin/env python
"""INT8 quantization coverage + ceiling analysis (VERDICT r3 weak #7).

Quantizes ResNet-50 (the graded int8 config) and accounts, node by node
over the quantized symbol with inferred shapes:

* what fraction of the model's FLOPs execute as int8 MXU ops,
* how many bytes the quantize/dequantize boundaries add,
* the resulting roofline prediction for int8-vs-fp32 speedup on v5e —
  i.e. whether the measured 1.76x is the kernel's fault or the
  boundary traffic's.

Run:  JAX_PLATFORMS=cpu python tools/int8_analysis.py
"""
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 128
V5E_BF16 = 197e12
V5E_INT8 = 394e12
V5E_HBM = 819e9


def conv_flops(attrs, in_shape, out_shape):
    k = eval(attrs.get("kernel", "(1, 1)")) if isinstance(
        attrs.get("kernel"), str) else attrs.get("kernel", (1, 1))
    cin = in_shape[1]
    n, cout, h, w = out_shape
    groups = int(attrs.get("num_group", 1))
    return 2 * n * cout * h * w * cin // groups * int(np.prod(k))


def fc_flops(in_shape, out_shape):
    return 2 * int(np.prod(in_shape)) * out_shape[-1]


def main():
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.contrib.quantization import quantize_model
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.symbol.symbol import _toposort

    mx.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Xavier())
    net.shape_init((1, 3, 224, 224))
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "r50")
        net.export(prefix)
        sym, args, aux = mx.model.load_checkpoint(prefix, 0)

    from incubator_mxnet_tpu.contrib.quantization import fold_batch_norm

    sym, args, aux = fold_batch_norm(sym, args, aux)
    qsym, qargs, qaux = quantize_model(sym, args, aux, calib_mode="none")

    from incubator_mxnet_tpu.symbol.symbol import _entry_key, _infer_graph

    known = {"data": (BATCH, 3, 224, 224)}
    for d in (qargs, qaux):
        for k, v in d.items():
            known[k] = tuple(v.shape)
    entry_shapes, _ = _infer_graph(qsym, known, {})

    int8_flops = 0
    f32_flops = 0
    boundary_bytes = 0
    n_boundary = {}
    per_node = []
    act_sizes = []

    def eshape(node, i=0):
        return entry_shapes.get(_entry_key(node, i))

    for node in _toposort([n for n, _ in qsym._outputs]):
        if node.is_var:
            continue
        out_shape = eshape(node)
        if out_shape is None:
            continue
        if node.op in ("_contrib_quantized_conv", "Convolution"):
            in_shape = eshape(*node.inputs[0])
            act_sizes.append(int(np.prod(out_shape)))
            fl = conv_flops(node.attrs, in_shape, out_shape)
            if node.op.startswith("_contrib_quantized"):
                int8_flops += fl
            else:
                f32_flops += fl
            per_node.append((node.name, node.op, fl))
        elif node.op in ("_contrib_quantized_fully_connected",
                         "FullyConnected"):
            in_shape = eshape(*node.inputs[0])
            fl = fc_flops(in_shape, out_shape)
            if node.op.startswith("_contrib_quantized"):
                int8_flops += fl
            else:
                f32_flops += fl
            per_node.append((node.name, node.op, fl))
        elif node.op in ("_contrib_quantize_v2", "_contrib_dequantize",
                         "_contrib_requantize"):
            # boundary op traffic per element: quantize f32r+i8w = 5,
            # dequantize i32r+f32w = 8, requantize i32r+i8w = 5
            elems = int(np.prod(out_shape))
            width = {"_contrib_quantize_v2": 5, "_contrib_dequantize": 8,
                     "_contrib_requantize": 5}[node.op]
            boundary_bytes += elems * width
            n_boundary[node.op] = n_boundary.get(node.op, 0) + 1

    total = int8_flops + f32_flops
    print("== int8 coverage (ResNet-50, batch %d) ==" % BATCH)
    print("conv/fc FLOPs as int8 : %.3e  (%.1f%%)"
          % (int8_flops, 100 * int8_flops / total))
    print("conv/fc FLOPs as f32  : %.3e  (%.1f%%)" % (f32_flops,
                                                      100 * f32_flops / total))
    print("boundary bytes/step   : %.3e (%.1f MB)" % (boundary_bytes,
                                                      boundary_bytes / 1e6))
    print("boundary node counts  : %s" % n_boundary)

    t_int8 = int8_flops / V5E_INT8
    t_f32_resid = f32_flops / V5E_BF16
    t_boundary = boundary_bytes / V5E_HBM
    t_bf16 = total / V5E_BF16
    print("\n== roofline prediction ==")
    print("bf16 all compute        : %.3f ms" % (1e3 * t_bf16))
    print("int8 mxu compute        : %.3f ms" % (1e3 * t_int8))
    print("UNFUSED boundary bound  : +%.3f ms (%.1f GB standalone "
          "requantize/quantize passes)" % (1e3 * t_boundary,
                                           boundary_bytes / 1e9))
    # with XLA fusion the requantize / quantized-add epilogues fold into
    # the conv output (the int32 accumulator never round-trips HBM): the
    # remaining activation traffic is the int8 tensors themselves
    act_elems = sum(fl_shape for fl_shape in act_sizes)
    t_act_int8 = act_elems * 1 / V5E_HBM
    t_act_bf16 = act_elems * 2 / V5E_HBM
    print("FUSED activation traffic: int8 %.3f ms vs bf16 %.3f ms"
          % (1e3 * t_act_int8, 1e3 * t_act_bf16))
    fused_int8 = max(t_int8, t_act_int8)
    fused_bf16 = max(t_bf16, t_act_bf16)
    print("fused ceiling (max of compute/BW roofs): int8 %.3f ms, "
          "bf16 %.3f ms -> %.2fx int8-over-bf16"
          % (1e3 * fused_int8, 1e3 * fused_bf16, fused_bf16 / fused_int8))
    print("unfused floor: %.2fx -> the measured speedup shows how much "
          "of the boundary XLA actually fused"
          % (t_bf16 / (t_int8 + t_f32_resid + t_boundary)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
