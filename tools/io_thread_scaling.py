#!/usr/bin/env python
"""ImageRecordIter thread-scaling benchmark.

Reference: ``src/io/iter_image_recordio_2.cc:28-76`` scales JPEG decode
by ``preprocess_threads`` across host cores.  This tool measures img/s
at several thread counts on THIS host and prints one JSON line per
point.  On a 1-core VM the curve is flat (decode is CPU-bound and the
GIL-released Pillow decode still shares one core) — run it on a
multi-core TPU host to see the real slope; the per-core decode cost it
prints is host-invariant and is the number PERF.md tracks.

Usage: python tools/io_thread_scaling.py [--images 512] [--threads 1,2,4,8]
"""
import argparse
import json
import os
import tempfile
import time

import numpy as np


def synth_shard(path, n=512, size=224):
    from incubator_mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, \
        pack_img

    rng = np.random.RandomState(0)
    rec = MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        rec.write_idx(i, pack_img(IRHeader(0, float(i % 10), i, 0), img,
                                  quality=90))
    rec.close()


def bench(prefix, threads, batch=64, size=224):
    from incubator_mxnet_tpu.io import ImageRecordIter

    it = ImageRecordIter(path_imgrec=prefix + ".rec",
                         path_imgidx=prefix + ".idx",
                         data_shape=(3, size, size), batch_size=batch,
                         shuffle=True, preprocess_threads=threads,
                         prefetch_buffer=4)
    n = 0
    next(it)  # warm the pipeline
    t0 = time.perf_counter()
    for b in it:
        n += b.data[0].shape[0]
    dt = time.perf_counter() - t0
    return n / dt, dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512)
    ap.add_argument("--threads", default="1,2,4,8")
    args = ap.parse_args()
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "shard")
        synth_shard(prefix, n=args.images)
        ncpu = os.cpu_count()
        for t in [int(x) for x in args.threads.split(",")]:
            img_s, dt = bench(prefix, t)
            print(json.dumps({
                "metric": "imagerecorditer_img_per_sec", "value":
                round(img_s, 1), "unit": "img/s", "preprocess_threads": t,
                "host_cores": ncpu,
                "ms_per_img_per_core": round(1e3 * min(t, ncpu) / img_s,
                                             3)}), flush=True)


if __name__ == "__main__":
    main()
