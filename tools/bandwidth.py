#!/usr/bin/env python
"""Communication-bandwidth measurement (reference ``tools/bandwidth/`` —
measure.py benchmarks kvstore push/pull throughput across devices).

Measures, on whatever devices are visible:
  * host->device and device->host transfer bandwidth (the PCIe test analog);
  * all-reduce (psum) bus bandwidth over the device mesh — the ICI path on
    a real TPU slice, ring-simulated on a forced-host CPU mesh
    (XLA_FLAGS=--xla_force_host_platform_device_count=8 for a dry run);
  * kvstore push/pull round-trip throughput, matching the reference tool's
    workload shape.

Usage::

    python tools/bandwidth.py [--size-mb 64] [--iters 10]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bw(nbytes, seconds):
    return nbytes / seconds / 1e9


def measure_transfer(size_mb: float, iters: int):
    import jax
    import numpy as np

    n = int(size_mb * 1e6 / 4)
    host = np.random.RandomState(0).rand(n).astype(np.float32)
    dev = jax.device_put(host)
    dev.block_until_ready()

    t = time.time()
    for _ in range(iters):
        dev = jax.device_put(host)
        dev.block_until_ready()
    h2d = _bw(host.nbytes * iters, time.time() - t)

    t = time.time()
    for _ in range(iters):
        _ = np.asarray(dev)
    d2h = _bw(host.nbytes * iters, time.time() - t)
    return h2d, d2h


def measure_allreduce(size_mb: float, iters: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        return None, len(devs)
    mesh = Mesh(np.array(devs), ("dp",))
    n = int(size_mb * 1e6 / 4)
    x = jnp.zeros((len(devs), n), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("dp", None)))

    @jax.jit
    def allreduce(x):
        # cross-shard sum: GSPMD lowers this to one all-reduce over the mesh
        return x.sum(0)

    y = allreduce(x)
    y.block_until_ready()
    t = time.time()
    for _ in range(iters):
        y = allreduce(x)
    y.block_until_ready()
    dt = (time.time() - t) / iters
    # ring all-reduce moves 2*(p-1)/p of the data per device
    p = len(devs)
    algo_bytes = 2 * (p - 1) / p * n * 4
    return _bw(algo_bytes * iters, dt * iters), p


def measure_kvstore(size_mb: float, iters: int):
    import numpy as np

    import incubator_mxnet_tpu as mx

    kv = mx.kv.create("device")
    n = int(size_mb * 1e6 / 4)
    val = mx.nd.array(np.random.RandomState(0).rand(n).astype(np.float32))
    kv.init("w", val)
    out = mx.nd.zeros((n,))
    kv.push("w", val)
    kv.pull("w", out=out)
    out.wait_to_read()
    t = time.time()
    for _ in range(iters):
        kv.push("w", val)
        kv.pull("w", out=out)
    out.wait_to_read()
    return _bw(val._data.nbytes * 2 * iters, time.time() - t)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    # honor $JAX_PLATFORMS even when a sitecustomize force-selects a platform
    # (same pin as bench.py) so the CPU-mesh dry run works
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass

    h2d, d2h = measure_transfer(args.size_mb, args.iters)
    print("host->device : %7.2f GB/s" % h2d)
    print("device->host : %7.2f GB/s" % d2h)
    ar, ndev = measure_allreduce(args.size_mb, args.iters)
    if ar is None:
        print("all-reduce   : skipped (1 device; set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "JAX_PLATFORMS=cpu for a mesh dry run)")
    else:
        print("all-reduce   : %7.2f GB/s bus bandwidth over %d devices"
              % (ar, ndev))
    kv_bw = measure_kvstore(args.size_mb, args.iters)
    print("kvstore push+pull: %7.2f GB/s" % kv_bw)


if __name__ == "__main__":
    main()
