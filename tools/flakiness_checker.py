#!/usr/bin/env python
"""Flakiness checker (``tools/flakiness_checker.py`` parity): rerun a test
N times with distinct seeds and report the failure rate.

Usage:
  python tools/flakiness_checker.py tests/test_operator.py::test_dropout -n 20
  python tools/flakiness_checker.py tests/test_rnn.py -n 10 --seed 7
"""
import argparse
import os
import subprocess
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("test", help="pytest node id (file[::test])")
    ap.add_argument("-n", "--trials", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed; trial i runs with seed base+i")
    ap.add_argument("--stop-on-fail", action="store_true")
    args = ap.parse_args()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    failures = []
    for i in range(args.trials):
        env = dict(os.environ, MXNET_TEST_SEED=str(args.seed + i),
                   PYTHONHASHSEED=str(args.seed + i))
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", args.test, "-x", "-q"],
            cwd=repo, env=env, capture_output=True, text=True)
        ok = proc.returncode == 0
        print("trial %2d seed=%d: %s" % (i, args.seed + i,
                                         "PASS" if ok else "FAIL"),
              flush=True)
        if not ok:
            failures.append((i, (proc.stdout + proc.stderr)[-1500:]))
            if args.stop_on_fail:
                break
    print("\n%d/%d trials failed" % (len(failures), args.trials))
    for i, log in failures[:3]:
        print("--- trial %d tail ---\n%s" % (i, log))
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
