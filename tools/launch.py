#!/usr/bin/env python
"""Local distributed-training launcher (reference ``tools/launch.py``).

Spawns N worker processes on this host with the ``DMLC_*`` rendezvous
environment the dist KVStore consumes (reference contract:
``tools/launch.py:71-113``; there are no separate scheduler/server roles —
workers rendezvous directly via jax.distributed, so ``-s`` is accepted for
CLI parity and ignored).

Usage::

    python tools/launch.py -n 4 python train.py --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(num_workers: int, command, port: int | None = None,
                 extra_env=None, grace: float = 20.0,
                 max_restarts: int = 0) -> int:
    """Spawn ``command`` num_workers times; return first nonzero exit.

    Failure detection (§5.3): worker liveness is polled (the launcher IS
    the heartbeat — ps-lite's tracker-side timeout analog).  If any worker
    dies nonzero, the survivors (likely blocked in a collective waiting
    for the dead peer) are terminated after ``grace`` seconds instead of
    hanging the launcher forever.

    Elastic recovery: with ``max_restarts > 0`` a failed job is relaunched
    whole, up to that many times, on a fresh rendezvous port.  XLA
    collectives are SPMD all-or-nothing, so whole-job restart + workers
    resuming from their last checkpoint (CheckpointHandler
    resume_from_checkpoint / Module --load-epoch pattern) is the recovery
    model; MXNET_RESTART_COUNT tells workers which attempt they are in.
    """
    attempt = 0
    while True:
        rc = _launch_once(num_workers, command, port, extra_env, grace,
                          attempt)
        if rc == 0 or attempt >= max_restarts:
            return rc
        attempt += 1
        print("[launch] job failed (rc=%d); restart %d/%d"
              % (rc, attempt, max_restarts), file=sys.stderr, flush=True)
        port = None  # new rendezvous


def _launch_once(num_workers: int, command, port, extra_env, grace: float,
                 attempt: int = 0) -> int:
    import time

    port = port or _free_port()
    procs = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(num_workers),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(rank),
            "MXNET_RESTART_COUNT": str(attempt),
        })
        if extra_env:
            env.update(extra_env)
        procs.append(subprocess.Popen(command, env=env))

    rc = 0
    failed_at = None
    while True:
        live = [p for p in procs if p.poll() is None]
        rc = rc or next((p.returncode for p in procs
                         if p.returncode not in (None, 0)), 0)
        if not live:
            break
        if rc and failed_at is None:
            failed_at = time.monotonic()
        if failed_at is not None and time.monotonic() - failed_at > grace:
            for p in live:
                p.terminate()
            for p in live:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            break
        time.sleep(0.2)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Launch a distributed job on this host.")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-CLI parity; ignored "
                         "(no parameter servers)")
    ap.add_argument("--launcher", default="local",
                    choices=["local"],
                    help="only local (single-host multi-process) here; "
                         "multi-host uses your cluster scheduler + "
                         "DMLC_* env directly")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="relaunch the whole job up to N times after a "
                         "worker failure (workers resume from their last "
                         "checkpoint; MXNET_RESTART_COUNT carries the "
                         "attempt number)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="training command to run")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    return launch_local(args.num_workers, args.command,
                        max_restarts=args.max_restarts)


if __name__ == "__main__":
    sys.exit(main())
