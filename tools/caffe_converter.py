#!/usr/bin/env python
"""Convert a Caffe model to this framework's checkpoint format.

Reference analog: ``tools/caffe_converter/convert_model.py`` CLI.

Usage:
    python tools/caffe_converter.py deploy.prototxt net.caffemodel out_prefix

Writes ``{out_prefix}-symbol.json`` and ``{out_prefix}-0000.params``
(stock checkpoint container), loadable with ``mx.model.load_checkpoint``.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prototxt")
    ap.add_argument("caffemodel")
    ap.add_argument("prefix")
    args = ap.parse_args()

    from incubator_mxnet_tpu import model
    from incubator_mxnet_tpu.contrib.caffe import convert_model

    with open(args.prototxt) as f:
        text = f.read()
    with open(args.caffemodel, "rb") as f:
        blob = f.read()
    sym, arg_params, aux_params = convert_model(text, blob)
    model.save_checkpoint(args.prefix, 0, sym, arg_params, aux_params)
    print("saved %s-symbol.json and %s-0000.params (%d args, %d aux)"
          % (args.prefix, args.prefix, len(arg_params), len(aux_params)))


if __name__ == "__main__":
    main()
