#!/usr/bin/env python
"""CI legs in one entrypoint (reference analog: ci/runtime_functions.sh —
unittest / quantization / sanity / nightly legs).

Legs:
  unit       pytest tests/ (CPU-pinned, 8-device virtual mesh)
  examples   the five graded example configs (pytest -m slow subset)
  tpu        pytest -m tpu (op consistency + int8 on the real chip)
  sanitize   C++ engine suite under ASAN and TSAN
  dryrun     8-device multichip sharding dry run (dp/tp/sp/pp/ep)
  all        everything above that the environment supports

Usage: python tools/ci.py [leg ...]
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(name, cmd, env=None, timeout=3600):
    t = time.time()
    print("== %s: %s" % (name, " ".join(cmd)), flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    rc = subprocess.run(cmd, cwd=REPO, env=e, timeout=timeout).returncode
    print("== %s: %s in %.0fs" % (name, "ok" if rc == 0 else
                                  "FAILED rc=%d" % rc, time.time() - t),
          flush=True)
    return rc


def leg_unit():
    return _run("unit", [sys.executable, "-m", "pytest", "tests/", "-q"])


def leg_examples():
    return _run("examples", [sys.executable, "-m", "pytest",
                             "tests/test_examples.py", "-q", "-m", "slow",
                             "--override-ini", "addopts="])


def leg_tpu():
    return _run("tpu", [sys.executable, "-m", "pytest", "tests/", "-q",
                        "-m", "tpu", "--override-ini", "addopts="])


def leg_sanitize():
    rc = _run("asan", ["make", "-C", "src/native", "asan-check"])
    return rc or _run("tsan", ["make", "-C", "src/native", "tsan-check"])


def leg_dryrun():
    return _run(
        "dryrun",
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        env={"JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})


LEGS = {"unit": leg_unit, "examples": leg_examples, "tpu": leg_tpu,
        "sanitize": leg_sanitize, "dryrun": leg_dryrun}


def main(argv):
    names = argv or ["all"]
    if names == ["all"]:
        names = ["unit", "examples", "dryrun", "sanitize", "tpu"]
    bad = [n for n in names if n not in LEGS]
    if bad:
        print("unknown legs: %s (have: %s)" % (bad, sorted(LEGS)))
        return 2
    rc = 0
    for n in names:
        rc = LEGS[n]() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
