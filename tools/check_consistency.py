#!/usr/bin/env python
"""TPU-vs-CPU operator consistency check (``check_consistency`` analog,
reference ``python/mxnet/test_utils.py:1422``: run the same op across
ctx/dtype combinations and cross-compare).

Runs a battery of registered ops on BOTH the TPU backend and the XLA-CPU
backend **in one process** (jax exposes both device sets) for float32 and
bfloat16 and asserts agreement within per-dtype tolerances.  This is the
pre-bench gate that catches TPU-lowering/precision bugs (bf16 matmul
accumulation, layout bugs, Mosaic kernel divergence) before the driver's
benchmark does.

Usage:  python tools/check_consistency.py        (needs a reachable TPU)
Exit status 0 = all ops agree; 1 = mismatch (details on stderr).
"""
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _cases():
    """(name, op, input arrays, attrs, needs_key) — the op families that
    carry the graded configs."""
    r = np.random.RandomState(0)

    def f(*shape):
        return r.normal(0, 1, shape).astype(np.float32)

    return [
        ("FullyConnected", "FullyConnected",
         [f(8, 32), f(16, 32), f(16)], {"num_hidden": 16}),
        ("dot", "dot", [f(16, 24), f(24, 8)], {}),
        ("batch_dot", "batch_dot", [f(4, 8, 12), f(4, 12, 6)], {}),
        ("Convolution", "Convolution",
         [f(2, 3, 16, 16), f(8, 3, 3, 3), f(8)],
         {"kernel": (3, 3), "num_filter": 8, "pad": (1, 1)}),
        ("Pooling_max", "Pooling", [f(2, 4, 12, 12)],
         {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
        ("Pooling_avg", "Pooling", [f(2, 4, 12, 12)],
         {"kernel": (2, 2), "stride": (2, 2), "pool_type": "avg"}),
        ("BatchNorm", "BatchNorm",
         [f(4, 6, 8, 8), np.abs(f(6)) + 0.5, f(6), f(6),
          np.abs(f(6)) + 0.5], {"fix_gamma": False}),
        ("LayerNorm", "LayerNorm", [f(4, 32), np.abs(f(32)) + 0.5, f(32)],
         {}),
        ("softmax", "softmax", [f(6, 50)], {}),
        ("log_softmax", "log_softmax", [f(6, 50)], {}),
        ("relu", "relu", [f(4, 64)], {}),
        ("sigmoid", "sigmoid", [f(4, 64)], {}),
        ("tanh", "tanh", [f(4, 64)], {}),
        ("exp", "exp", [f(4, 64) * 0.3], {}),
        ("sum", "sum", [f(4, 8, 16)], {"axis": (1, 2)}),
        ("mean", "mean", [f(4, 8, 16)], {"axis": 1}),
        ("max", "max", [f(4, 8, 16)], {"axis": 2}),
        ("broadcast_add", "broadcast_add", [f(4, 1, 8), f(1, 6, 8)], {}),
        ("broadcast_mul", "broadcast_mul", [f(4, 6, 1), f(4, 1, 8)], {}),
        ("transpose", "transpose", [f(3, 4, 5)], {"axes": (2, 0, 1)}),
        ("take", "take", [f(10, 4),
                          np.array([0, 3, 7, 9], np.float32)], {}),
        ("topk", "topk", [f(4, 32)], {"k": 5, "ret_typ": "value"}),
        ("norm", "norm", [f(4, 16)], {"ord": 2, "axis": 1}),
    ]


_MXU_OPS = {"FullyConnected", "dot", "batch_dot", "Convolution"}


def _tol(dtype, name):
    """Per-dtype tolerance; MXU (matmul/conv) ops compare looser in f32
    because the TPU's default f32 matmul path multiplies in bf16 with f32
    accumulation (3-pass), which is the configuration the framework ships
    (the reference's check_consistency likewise keys tolerance on ctx+dtype,
    test_utils.py:1422)."""
    if name.split("_")[0] in _MXU_OPS or name in _MXU_OPS:
        # bf16 multiply eps is 2^-8 ≈ 4e-3 of the operand scale; accumulated
        # over the contraction the absolute error is ~1e-2 of max|out|
        return {"float32": (2e-2, 1e-2), "bfloat16": (6e-2, 2e-2)}[dtype]
    return {"float32": (1e-4, 1e-5), "bfloat16": (5e-2, 5e-3)}[dtype]


_SWEEP_MXU = ("FullyConnected", "dot", "Dot", "batch_dot", "Convolution",
              "Deconvolution", "Correlation", "_contrib_interleaved_matmul",
              "_npi_einsum", "_npi_tensordot", "_npi_matmul", "_npi_dot",
              "_npi_vdot", "_npi_inner", "_npi_outer", "_npi_kron", "RNN",
              "_linalg_gemm", "_linalg_trmm", "_linalg_trsm", "_linalg_syrk",
              "_contrib_DeformableConvolution", "khatri_rao",
              "_npi_tensorinv", "_npi_tensorsolve", "_contrib_quantized")


def _sweep_tol(opname, dtype="float32"):
    mxu = any(opname.startswith(p) or opname == p for p in _SWEEP_MXU)
    if dtype == "bfloat16":
        # bf16 eps 2^-8: both backends quantize identically, but fusion /
        # accumulation order differs across compilers
        return (1e-1, 5e-2) if mxu else (5e-2, 1e-2)
    return (2e-2, 1e-2) if mxu else (1e-4, 1e-5)


def run_registry_sweep(jax, jnp, reg, cpu_dev, tpu_dev, failures,
                       dtypes=("float32", "bfloat16")):
    """Full-registry TPU-vs-CPU forward battery over the reflection-
    synthesized cases (tools/op_sweep.py) — every op with a synthesizable
    signature executes on the TPU backend, not just the curated battery,
    in f32 AND bf16 (the dtype the headline bench actually runs).
    Host-eval (no_trace) ops run on the host by construction and are
    skipped; skips are counted, never silent."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from op_sweep import build_cases

    cases, uncovered = build_cases()
    n = 0
    skipped = list(uncovered)
    for name in sorted(cases):
        op = reg.get_op(name)
        if op.no_trace:
            skipped.append(name)
            continue
        arrays, attrs = cases[name]
        attrs = dict(attrs)
        if attrs.get("key") == "sweep" or op.needs_rng:
            attrs["key"] = jax.random.PRNGKey(11)
        for dtype in dtypes:
            rtol, atol = _sweep_tol(name, dtype)
            cast = [np.asarray(a, jnp.bfloat16)
                    if (dtype == "bfloat16"
                        and np.issubdtype(np.asarray(a).dtype, np.floating))
                    else a for a in arrays]
            if dtype == "bfloat16" and all(c is a for c, a in
                                           zip(cast, arrays)):
                continue  # no float inputs: the f32 leg already covers it
            try:
                outs = {}
                for tag, dev in (("cpu", cpu_dev), ("tpu", tpu_dev)):
                    args = [jax.device_put(jnp.asarray(a), dev)
                            for a in cast]
                    key = attrs.get("key")
                    if key is not None:
                        attrs["key"] = jax.device_put(key, dev)
                    o = jax.jit(lambda *xs: op.fn(*xs, **attrs))(*args)
                    outs[tag] = o if isinstance(o, (tuple, list)) else (o,)
                for oc, ot in zip(outs["cpu"], outs["tpu"]):
                    ref = np.asarray(oc, np.float32)
                    got = np.asarray(ot, np.float32)
                    scale = float(np.abs(ref).max()) if ref.size else 1.0
                    np.testing.assert_allclose(ref, got, rtol=rtol,
                                               atol=atol * max(scale, 1.0))
                if dtype == "float32":
                    n += 1
            except AssertionError as e:
                failures.append(("sweep:" + name, dtype,
                                 str(e).split("\n")[0]))
            except Exception as e:
                err = traceback.format_exc(limit=1).strip().replace("\n",
                                                                    " ")
                # only a dtype-CONTRACT rejection counts as a documented
                # bf16 skip; any other exception (compiler crash, wrong
                # shape, runtime error) is a real failure — a bf16-only
                # lowering bug must not pass the gate as a skip
                dtype_strict = any(
                    pat in (str(e) + type(e).__name__).lower()
                    for pat in ("dtype", "bfloat16", "unsupported",
                                "not implemented", "must be a float",
                                "not supported"))
                if dtype == "bfloat16" and dtype_strict:
                    skipped.append(name + ":bf16-unsupported")
                else:
                    failures.append(("sweep:" + name, dtype, err))
    return n, skipped


def main():
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops import registry as reg

    self_test = "--self-test" in sys.argv
    tpu_devs = [d for d in jax.devices() if d.platform == "tpu"]
    if not tpu_devs and not self_test:
        print(json.dumps({"skipped": "no tpu device"}))
        return 0
    cpu_dev = jax.devices("cpu")[0]
    if self_test:
        # harness validation without a chip: compare cpu against itself
        # (any failure is a sweep-plumbing bug, not a backend divergence)
        cpus = jax.devices("cpu")
        tpu_dev = cpus[1] if len(cpus) > 1 else cpus[0]
    else:
        tpu_dev = tpu_devs[0]

    failures = []
    n_checked = 0
    for dtype in ("float32", "bfloat16"):
        for name, opname, arrays, attrs in _cases():
            rtol, atol = _tol(dtype, name)
            op = reg.get_op(opname)
            try:
                args_c, args_t = [], []
                for a in arrays:
                    x = jnp.asarray(a)
                    if dtype == "bfloat16" and x.dtype == jnp.float32:
                        x = x.astype(jnp.bfloat16)
                    args_c.append(jax.device_put(x, cpu_dev))
                    args_t.append(jax.device_put(x, tpu_dev))
                out_c = jax.jit(
                    lambda *xs: op.fn(*xs, **attrs))(*args_c)
                out_t = jax.jit(
                    lambda *xs: op.fn(*xs, **attrs))(*args_t)
                oc = out_c[0] if isinstance(out_c, (tuple, list)) else out_c
                ot = out_t[0] if isinstance(out_t, (tuple, list)) else out_t
                ref = np.asarray(oc, np.float32)
                got = np.asarray(ot, np.float32)
                # atol scales with the output magnitude: MXU rounding error
                # is absolute in units of max|out|, so near-zero elements of
                # a matmul must not be held to a pure relative bound
                scale = float(np.abs(ref).max()) if ref.size else 1.0
                np.testing.assert_allclose(
                    ref, got, rtol=rtol, atol=atol * max(scale, 1.0))
                n_checked += 1
            except AssertionError as e:
                failures.append((name, dtype, str(e).split("\n")[0]))
            except Exception:
                failures.append((name, dtype, traceback.format_exc(
                    limit=1).strip().replace("\n", " ")))

    # flash attention: compiled Mosaic kernel vs CPU interpret mode
    try:
        from incubator_mxnet_tpu.parallel.ring_attention import (
            attention_reference)
        import importlib

        fa = importlib.import_module(
            "incubator_mxnet_tpu.parallel.flash_attention")
        r = np.random.RandomState(1)
        q, k, v = (jnp.asarray(
            r.normal(size=(2, 2, 256, 64)).astype(np.float32)) * 0.2
            for _ in range(3))
        out_t = jax.jit(lambda q, k, v: fa.flash_attention(
            q, k, v, causal=True, interpret=False))(
            *(jax.device_put(x, tpu_dev) for x in (q, k, v)))
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out_t), np.asarray(ref),
                                   rtol=2e-2, atol=2e-3)
        n_checked += 1
    except AssertionError as e:
        failures.append(("flash_attention", "float32",
                         str(e).split("\n")[0]))

    n_sweep, sweep_skipped = run_registry_sweep(jax, jnp, reg, cpu_dev,
                                                tpu_dev, failures)
    result = {"checked": n_checked, "sweep_ops": n_sweep,
              "sweep_skipped": sorted(sweep_skipped),
              "failures": len(failures)}
    if failures:
        for name, dtype, msg in failures:
            print("FAIL %s[%s]: %s" % (name, dtype, msg), file=sys.stderr)
        print(json.dumps(result))
        return 1
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
