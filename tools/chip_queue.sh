#!/bin/bash
# Chip-blocked measurement queue (round-5).  Run when the TPU tunnel is
# reachable; each step is independently timeboxed and failures don't
# stop the rest.  Probe first:
#   timeout 240 python -c 'import jax; jax.devices()' && bash tools/chip_queue.sh
set -u
cd "$(dirname "$0")/.."
LOG=${1:-chip_queue_results.txt}
{
echo "== chip queue $(date -u +%FT%TZ) =="

echo "-- 1. headline bench, stock config (warm cache expected)"
# --no-config alone now means the round-19 composed default (ghost-BN 16
# + byte-diet passes); the sweep baseline must be TRUE stock BatchNorm
timeout 580 python bench.py --chunks 3 --no-config --ghost-bn 0 --passes '' \
    | tee /tmp/bench_stock.txt

echo "-- 2. per-kernel BN DMA-efficiency microbench (VERDICT r4 item 1)"
timeout 1200 python tools/bn_kernel_bench.py --residual \
    --out bn_kernel_results.jsonl

echo "-- 3. perf variant sweep (absorb proven wins into the default)"
timeout 900 python bench.py --chunks 3 --no-config --s2d-stem --ghost-bn 0 \
    --passes '' | tee /tmp/bench_s2d.txt
timeout 900 python bench.py --chunks 3 --no-config --ghost-bn 16 --passes '' \
    | tee /tmp/bench_gbn.txt
timeout 1200 python bench.py --chunks 3 --no-config --s2d-stem --ghost-bn 16 \
    --passes '' | tee /tmp/bench_both.txt
timeout 1200 python bench.py --chunks 3 --no-config \
    | tee /tmp/bench_composed.txt

echo "-- 4. pick the measured winner -> bench_config.json"
python - <<'EOF'
import json

def rows(path):
    try:
        return [json.loads(l) for l in open(path)
                if l.startswith('{"metric"')]
    except OSError:
        return []

# img/s across batches is not comparable (bench.py falls back
# 256->128->... on OOM), so compare at the batch the STOCK run actually
# achieved — same-batch guarantee without a hard 256 dependency
stock_rows = rows("/tmp/bench_stock.txt")
ref_batch = max((r.get("batch", 0) for r in stock_rows), default=256)

def best(path, **flags):
    v = max((r.get("value", 0.0) for r in rows(path)
             if r.get("batch") == ref_batch), default=0.0)
    return v, flags

runs = [
    best("/tmp/bench_stock.txt", ghost_bn=0, passes=""),
    best("/tmp/bench_s2d.txt", s2d_stem=True, ghost_bn=0, passes=""),
    best("/tmp/bench_gbn.txt", ghost_bn=16, passes=""),
    best("/tmp/bench_both.txt", s2d_stem=True, ghost_bn=16, passes=""),
    # the round-19 composed default (ghost-BN 16 + byte-diet passes)
    best("/tmp/bench_composed.txt",
         ghost_bn=16, passes="space_to_depth,maxpool_bwd_mask"),
]
# the flagless driver run uses the composed round-19 default, so THAT
# leg is the baseline to beat; a written config (incl. ghost_bn=0 if
# stock BN somehow wins) overrides it
stock, default_v = runs[0][0], runs[-1][0]
win_v, win_flags = max(runs, key=lambda r: r[0])
print("stock %.1f, composed default %.1f; winner %.1f img/s %s"
      % (stock, default_v, win_v, win_flags))
if win_v > default_v * 1.01:
    win_flags["measured"] = "%.1f img/s vs composed default %.1f" \
        % (win_v, default_v)
    json.dump(win_flags, open("bench_config.json", "w"), indent=1)
    print("wrote bench_config.json:", win_flags)
else:
    # a stale config from an earlier round would keep overriding the
    # now-winning default on every flagless driver run
    import os
    if os.path.exists("bench_config.json"):
        os.remove("bench_config.json")
        print("removed stale bench_config.json")
    print("composed default stands (no variant beat it by >1%)")
EOF

echo "-- 5. headline with the absorbed config (this is BENCH_r05's config)"
# composed default pays the GL301 pass probes at build — same budget as
# the step-3 composed leg
timeout 1200 python bench.py --chunks 3

echo "-- 6. inference (bf16 batch-128 vs the V100 fp16 BASELINE row)"
timeout 580 python bench.py --mode infer

echo "-- 6b. int8 inference through the wire"
timeout 580 python bench.py --mode infer-int8

echo "-- 7. TPU consistency gate (375-op sweep + int8-wire resnet)"
timeout 2700 python -m pytest tests/ -m tpu -q

echo "-- 8. recordio-fed training (host-core bound on 1-vCPU driver)"
timeout 1200 python bench.py --data recordio --record-format .npy --chunks 3

echo "-- 9. attention (XLA default headline + Pallas long-seq crossover)"
timeout 900 python bench.py --mode attention

echo "-- 10. per-op TPU latency sweep (hot ResNet-50 ops + default set)"
timeout 580 python benchmark/opperf.py --resnet --json opperf_resnet.json
timeout 580 python benchmark/opperf.py --json opperf_default.json

echo "-- 11. IO thread scaling (flat on a 1-core driver; per-core cost is the tracked number)"
timeout 420 python tools/io_thread_scaling.py --images 256

echo "== done $(date -u +%FT%TZ) =="
} 2>&1 | tee "$LOG"
