#!/bin/bash
# Chip-blocked measurement queue (round-5).  Run when the TPU tunnel is
# reachable; each step is independently timeboxed and failures don't
# stop the rest.  Probe first:
#   timeout 240 python -c 'import jax; jax.devices()' && bash tools/chip_queue.sh
#
# CHIP_QUEUE_DRY_RUN=1 exercises the queue's WIRING on the CPU backend
# without burning chip time: heavy measurement legs are printed and
# skipped, while the artifact-producing legs (kernel-variant sweep,
# train-schedule winner) run tiny CPU workloads end-to-end and their
# output contracts are validated — this is what tests/test_tools.py
# runs in tier-1, so a flag/json drift in the queue fails BEFORE a
# chip window is spent discovering it.
set -u
cd "$(dirname "$0")/.."
DRY=${CHIP_QUEUE_DRY_RUN:-0}
if [ "$DRY" = "1" ]; then
    export JAX_PLATFORMS=cpu
fi

# run <timeout_s> <cmd...> — dry mode prints the command and skips it
run() {
    local t=$1; shift
    if [ "$DRY" = "1" ]; then
        echo "[dry-run] skip (${t}s): $*"
        return 0
    fi
    timeout "$t" "$@"
}

LOG=${1:-chip_queue_results.txt}
{
echo "== chip queue $(date -u +%FT%TZ) =="

echo "-- 1. headline bench, stock config (warm cache expected)"
# --no-config alone now means the round-19 composed default (ghost-BN 16
# + byte-diet passes); the sweep baseline must be TRUE stock BatchNorm
run 580 python bench.py --chunks 3 --no-config --ghost-bn 0 --passes '' \
    | tee /tmp/bench_stock.txt

echo "-- 2. per-kernel BN DMA-efficiency microbench (VERDICT r4 item 1)"
run 1200 python tools/bn_kernel_bench.py --residual \
    --out bn_kernel_results.jsonl

echo "-- 2b. round-20 kernel-variant sweep (lane-fold stem + spatial-tiled"
echo "       exits vs whole-L vs stock XLA, JSON artifact)"
if [ "$DRY" = "1" ]; then
    rm -f /tmp/bn_kernel_variants.json
    timeout 300 python tools/bn_kernel_bench.py --variants --dry-run \
        --format json --out /tmp/bn_kernel_variants.json \
        && python -c "
import json
rows = [json.loads(l) for l in open('/tmp/bn_kernel_variants.json')]
assert rows and all('variant' in r and 'stock_xla_ms' in r for r in rows), rows
print('kernel-variant sweep contract ok: %d rows' % len(rows))"
else
    run 1800 python tools/bn_kernel_bench.py --variants --residual \
        --format json --out bn_kernel_variants.json
fi

echo "-- 3. perf variant sweep (absorb proven wins into the default)"
run 900 python bench.py --chunks 3 --no-config --s2d-stem --ghost-bn 0 \
    --passes '' | tee /tmp/bench_s2d.txt
run 900 python bench.py --chunks 3 --no-config --ghost-bn 16 --passes '' \
    | tee /tmp/bench_gbn.txt
run 1200 python bench.py --chunks 3 --no-config --s2d-stem --ghost-bn 16 \
    --passes '' | tee /tmp/bench_both.txt
run 1200 python bench.py --chunks 3 --no-config \
    | tee /tmp/bench_composed.txt

echo "-- 4. pick the measured winner -> bench_config.json"
python - <<'EOF'
import json

def rows(path):
    try:
        return [json.loads(l) for l in open(path)
                if l.startswith('{"metric"')]
    except OSError:
        return []

# img/s across batches is not comparable (bench.py falls back
# 256->128->... on OOM), so compare at the batch the STOCK run actually
# achieved — same-batch guarantee without a hard 256 dependency
stock_rows = rows("/tmp/bench_stock.txt")
ref_batch = max((r.get("batch", 0) for r in stock_rows), default=256)

def best(path, **flags):
    v = max((r.get("value", 0.0) for r in rows(path)
             if r.get("batch") == ref_batch), default=0.0)
    return v, flags

runs = [
    best("/tmp/bench_stock.txt", ghost_bn=0, passes=""),
    best("/tmp/bench_s2d.txt", s2d_stem=True, ghost_bn=0, passes=""),
    best("/tmp/bench_gbn.txt", ghost_bn=16, passes=""),
    best("/tmp/bench_both.txt", s2d_stem=True, ghost_bn=16, passes=""),
    # the round-19 composed default (ghost-BN 16 + byte-diet passes)
    best("/tmp/bench_composed.txt",
         ghost_bn=16, passes="space_to_depth,maxpool_bwd_mask"),
]
# the flagless driver run uses the composed round-19 default, so THAT
# leg is the baseline to beat; a written config (incl. ghost_bn=0 if
# stock BN somehow wins) overrides it
stock, default_v = runs[0][0], runs[-1][0]
win_v, win_flags = max(runs, key=lambda r: r[0])
print("stock %.1f, composed default %.1f; winner %.1f img/s %s"
      % (stock, default_v, win_v, win_flags))
if win_v > default_v * 1.01:
    win_flags["measured"] = "%.1f img/s vs composed default %.1f" \
        % (win_v, default_v)
    json.dump(win_flags, open("bench_config.json", "w"), indent=1)
    print("wrote bench_config.json:", win_flags)
else:
    # a stale config from an earlier round would keep overriding the
    # now-winning default on every flagless driver run
    import os
    if os.path.exists("bench_config.json"):
        os.remove("bench_config.json")
        print("removed stale bench_config.json")
    print("composed default stands (no variant beat it by >1%)")
EOF

echo "-- 4b. graftsched train-schedule winner vs the hand-built default"
# zero-compile per-site schedule search over the byte-diet passes; the
# winner JSON is the exact artifact bench.py --schedule-config consumes
# (knobs.schedule canonical dict + knobs.schedule_hash stamp)
if [ "$DRY" = "1" ]; then
    timeout 300 python tools/autotune.py --target train-schedule \
        --model conv-bn --passes space_to_depth,maxpool_bwd_mask \
        --batches 8 --budget-compiles 0 \
        --winner-out /tmp/sched_winner.json \
        && python -c "
import json
from incubator_mxnet_tpu.analysis.passes import PassSchedule
w = json.load(open('/tmp/sched_winner.json'))
h = PassSchedule.from_dict(w['knobs']['schedule']).hash()
assert h == w['knobs']['schedule_hash'], (h, w['knobs'])
print('schedule-winner contract ok: hash', h)"
else
    run 900 python tools/autotune.py --target train-schedule \
        --model resnet50 --passes space_to_depth,maxpool_bwd_mask \
        --batches 32 --budget-compiles 0 \
        --winner-out /tmp/sched_winner.json
    run 1200 python bench.py --chunks 3 --no-config \
        --schedule-config /tmp/sched_winner.json \
        | tee /tmp/bench_schedwin.txt
    python - <<'EOF'
import json

def best(path):
    try:
        return max((json.loads(l).get("value", 0.0) for l in open(path)
                    if l.startswith('{"metric"')), default=0.0)
    except OSError:
        return 0.0

hand = best("/tmp/bench_composed.txt")
win = best("/tmp/bench_schedwin.txt")
if hand and win:
    print("schedule winner %.1f img/s vs hand-built default %.1f img/s "
          "(%+.1f%%)" % (win, hand, 100.0 * (win - hand) / hand))
else:
    print("schedule-winner delta unavailable (hand=%.1f winner=%.1f)"
          % (hand, win))
EOF
fi

echo "-- 5. headline with the absorbed config (this is BENCH_r05's config)"
# composed default pays the GL301 pass probes at build — same budget as
# the step-3 composed leg
run 1200 python bench.py --chunks 3

echo "-- 6. inference (bf16 batch-128 vs the V100 fp16 BASELINE row)"
run 580 python bench.py --mode infer

echo "-- 6b. int8 inference through the wire"
run 580 python bench.py --mode infer-int8

echo "-- 7. TPU consistency gate (375-op sweep + int8-wire resnet)"
run 2700 python -m pytest tests/ -m tpu -q

echo "-- 8. recordio-fed training (host-core bound on 1-vCPU driver)"
run 1200 python bench.py --data recordio --record-format .npy --chunks 3

echo "-- 9. attention (XLA default headline + Pallas long-seq crossover)"
run 900 python bench.py --mode attention

echo "-- 10. per-op TPU latency sweep (hot ResNet-50 ops + default set)"
run 580 python benchmark/opperf.py --resnet --json opperf_resnet.json
run 580 python benchmark/opperf.py --json opperf_default.json

echo "-- 11. IO thread scaling (flat on a 1-core driver; per-core cost is the tracked number)"
run 420 python tools/io_thread_scaling.py --images 256

echo "== done $(date -u +%FT%TZ) =="
} 2>&1 | tee "$LOG"
