#!/bin/bash
# Chip-blocked measurement queue (round-4 tunnel outage backlog).
# Run when the TPU tunnel is reachable; each step is independently
# timeboxed and failures don't stop the rest.  Probe first:
#   curl -m5 127.0.0.1:8083 >/dev/null && bash tools/chip_queue.sh
set -u
cd "$(dirname "$0")/.."
LOG=${1:-chip_queue_results.txt}
{
echo "== chip queue $(date -u +%FT%TZ) =="

echo "-- 1. headline bench (warm cache expected: compile <10s)"
timeout 580 python bench.py --chunks 3

echo "-- 2. int8 inference through the round-4 wire"
timeout 580 python bench.py --mode infer-int8

echo "-- 3. TPU consistency gate (375-op sweep + int8-wire resnet)"
timeout 1500 python -m pytest tests/ -m tpu -q

echo "-- 4. recordio-fed training (host-core bound on 1-vCPU driver)"
timeout 580 python bench.py --data recordio --record-format .npy --chunks 3

echo "-- 5. attention (XLA default headline + Pallas comparison)"
timeout 580 python bench.py --mode attention

echo "== done $(date -u +%FT%TZ) =="
} 2>&1 | tee "$LOG"
