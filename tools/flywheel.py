#!/usr/bin/env python
"""Closed train→serve flywheel demo (docs/RESILIENCE.md §9).

One process, the whole loop:

1. a ServeEngine + ContinuousBatcher serve open-loop Poisson traffic,
   and the served payloads are RECORDED — loadtest traffic becomes the
   training stream (labels come from a fixed deterministic teacher
   projection, so the run is reproducible);
2. a supervised trainer (``parallel/supervisor.py::run_supervised`` —
   divergence rollback, atomic elastic checkpoints every
   ``--checkpoint-every`` steps) consumes that stream through
   ``ResilientIter`` in a background thread;
3. the promotion daemon (``serve/flywheel.py``) watches the checkpoint
   dir — committed steps only — and walks each candidate through the
   gauntlet (checksummed load → held-out metric vs the incumbent →
   GL011 + graftrange + canary), hot-swapping survivors into the live
   engine UNDER the serving load and appending every verdict to the
   JSONL promotion ledger.

Chaos legs close the loop in both directions:

- ``--chaos loss_bomb`` plants a finite gradient bomb mid-stream: the
  supervisor must roll training back (ledger: divergence → rollback →
  recovered), and a force-committed DIVERGED checkpoint must be
  quarantined by the gauntlet with ZERO promoted versions from it —
  the serving engine's ``rollback_count`` stays 0 because the metric
  stage rejects before the swap path;
- ``--chaos swap_storm`` fires N back-to-back promotions (one
  poisoned) under sustained load: p99 must hold the declared bound,
  0 post-warmup recompiles, exactly-one-version attribution on every
  row, incumbent restored bitwise on the poison.

Reports JSON lines (the bench.py convention); exit 1 on any broken
contract.

Examples::

  JAX_PLATFORMS=cpu python tools/flywheel.py --steps 10 --qps 200
  JAX_PLATFORMS=cpu python tools/flywheel.py --chaos loss_bomb
  JAX_PLATFORMS=cpu python tools/flywheel.py --chaos swap_storm
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = time.time()


def log(msg):
    print("[flywheel %6.1fs] %s" % (time.time() - T0, msg),
          file=sys.stderr, flush=True)


#: the tiny flywheel model (tools/supervise.py's worker job shape):
#: 16-dim requests, 13 classes
IN_DIM, N_CLASSES = 16, 13


def build_net(seed=0):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    net = nn.HybridSequential()
    for _ in range(2):
        net.add(nn.Dense(16, activation="tanh"))
    net.add(nn.Dense(N_CLASSES))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, IN_DIM)))
    return net


def teacher_labels(X):
    """Deterministic labels for recorded traffic: argmax of a fixed
    random projection, with 30% label noise.  The noise matters for the
    chaos leg — without it a loss-bombed (weight-saturated) net can be
    confidently RIGHT on whole teacher-labeled batches, interleaving
    zero-CE steps that hold the divergence detector's loss EMA under
    its explosion threshold.  Noisy rows pin every post-bomb batch at a
    huge finite CE, so the verdict confirms the way real garbage
    traffic would."""
    import numpy as np

    W = np.random.RandomState(7).randn(IN_DIM, N_CLASSES)
    Y = np.argmax(np.asarray(X) @ W, axis=1).astype(np.float32)
    nz = np.random.RandomState(11)
    flip = nz.rand(len(Y)) < 0.3
    Y[flip] = nz.randint(0, N_CLASSES, int(flip.sum())).astype(np.float32)
    return Y


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=10,
                    help="trainer steps (checkpoints land every "
                         "--checkpoint-every)")
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--qps", type=float, default=200.0)
    ap.add_argument("--requests", type=int, default=120,
                    help="requests per loadtest window (capture + live)")
    ap.add_argument("--chaos", choices=("loss_bomb", "swap_storm"),
                    default=None)
    ap.add_argument("--dir", default=None,
                    help="working dir (default: a fresh tempdir)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    import numpy as np

    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.io import NDArrayIter, ResilientIter
    from incubator_mxnet_tpu.parallel import (CheckpointManager,
                                              SupervisorConfig,
                                              make_train_step,
                                              run_supervised)
    from incubator_mxnet_tpu.parallel import fault_injection as fi
    from incubator_mxnet_tpu.parallel.supervisor import read_ledger
    from incubator_mxnet_tpu.serve import (ContinuousBatcher,
                                           PromotionDaemon, ServeEngine,
                                           load_candidate_params,
                                           poisson_loadtest,
                                           read_promotions)

    outdir = args.dir or tempfile.mkdtemp(prefix="flywheel-")
    os.makedirs(outdir, exist_ok=True)
    failures = []

    # -- serving side: engine + batcher, warmed (recompile_count pins 0)
    eng = ServeEngine(build_net(seed=args.seed), buckets=(8, 16),
                      lint="error", numerics="error")
    eng.warmup(np.zeros((IN_DIM,), np.float32))
    batcher = ContinuousBatcher(eng, max_delay=0.005, max_queue=1024)

    # -- phase 1: serve AND capture the traffic as the training stream
    rs = np.random.RandomState(args.seed)
    pool = rs.rand(64, IN_DIM).astype(np.float32)
    captured = []

    def payload(i, rng):
        row = pool[i % 64]
        captured.append(row)
        return row

    cap = poisson_loadtest(batcher, payload, qps=args.qps,
                           n_requests=args.requests, seed=args.seed,
                           extra={"leg": "capture"})
    log("capture: " + cap.format())
    X = np.stack(captured)
    Y = teacher_labels(X)

    # -- trainer over the recorded stream (same-lineage init: the
    # incumbent is where training starts, candidates drift mildly)
    tnet = build_net(seed=args.seed)
    step = make_train_step(tnet, gluon.loss.SoftmaxCrossEntropyLoss(),
                           optimizer="adam", learning_rate=0.01,
                           lint="error")
    np.random.seed(3)
    it = ResilientIter(NDArrayIter(X, Y, batch_size=8, shuffle=True))
    mgr = CheckpointManager(os.path.join(outdir, "ckpt"))
    cfg = SupervisorConfig(checkpoint_every=args.checkpoint_every)

    train_out = {}

    def train():
        try:
            if args.chaos == "loss_bomb":
                with fi.loss_bomb(at=4, factor=1e4) as st:
                    train_out.update(run_supervised(
                        step, it, mgr, until_step=args.steps, config=cfg))
                train_out["bomb_fired"] = st.fired
            else:
                train_out.update(run_supervised(
                    step, it, mgr, until_step=args.steps, config=cfg))
        except BaseException as e:  # surfaced below, never silent
            train_out["error"] = "%s: %s" % (type(e).__name__, e)

    # -- promotion daemon: held-out rows from the captured stream
    daemon = PromotionDaemon(mgr, eng, held_out=(X[:16], Y[:16]),
                             metric_slack=0.5)
    stop = threading.Event()

    def promote():
        while not stop.is_set():
            daemon.poll_once(timeout=0.2)

    tthread = threading.Thread(target=train, name="flywheel-trainer")
    pthread = threading.Thread(target=promote, name="flywheel-daemon",
                               daemon=True)
    tthread.start()
    pthread.start()

    # -- phase 2: live window — promotions land UNDER this traffic
    live = poisson_loadtest(batcher, lambda i, rng: pool[i % 64],
                            qps=args.qps, n_requests=args.requests,
                            seed=args.seed + 1, extra={"leg": "live"})
    log("live:    " + live.format())
    tthread.join(timeout=300.0)
    if tthread.is_alive():
        failures.append("trainer failed to finish")
    if train_out.get("error"):
        failures.append("trainer error: %s" % train_out["error"])
    # drain the daemon: every committed candidate gets its verdict
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        newest = mgr.latest_committed()
        if newest is None or daemon.last_processed == newest:
            break
        time.sleep(0.1)

    storm_rec = None
    if args.chaos == "loss_bomb":
        # the diverged-checkpoint arm: training rolled back, and a
        # force-committed diverged candidate must be quarantined with
        # zero promoted versions from it
        if train_out.get("rollbacks", 0) < 1:
            failures.append("loss_bomb did not trigger a training "
                            "rollback")
        events = [e["event"] for e in read_ledger(str(mgr.directory))]
        for want in ("divergence", "rollback", "recovered"):
            if want not in events:
                failures.append("training ledger missing %r" % want)
        newest = mgr.latest_committed()
        raw = load_candidate_params(mgr, newest)
        promoted_before = daemon.promoted_count
        rb_before = eng.rollback_count
        mgr.save(newest + 1,
                 {"params": [np.asarray(a) * 1e4 for a in raw]})
        deadline = time.monotonic() + 60.0
        while daemon.last_processed != newest + 1 \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        if daemon.last_processed != newest + 1:
            failures.append("daemon never saw the diverged candidate")
        if daemon.promoted_count != promoted_before:
            failures.append("a DIVERGED checkpoint was promoted")
        if eng.rollback_count != rb_before:
            failures.append("diverged candidate reached the canary "
                            "(metric stage should reject first)")
    stop.set()
    pthread.join(timeout=10.0)

    if args.chaos == "swap_storm":
        with fi.swap_storm(eng, n_swaps=6, interval=0.02, poison_at=3,
                           seed=args.seed) as st:
            storm = poisson_loadtest(batcher,
                                     lambda i, rng: pool[i % 64],
                                     qps=args.qps,
                                     n_requests=args.requests,
                                     seed=args.seed + 2,
                                     extra={"leg": "swap_storm"})
        log("storm:   " + storm.format())
        bound_ms = live.p99_ms * 10.0 + 250.0
        if storm.p99_ms > bound_ms:
            failures.append("storm p99 %.2fms beyond bound %.2fms"
                            % (storm.p99_ms, bound_ms))
        if storm.hung or storm.unattributed:
            failures.append("storm: %d hung, %d unattributed"
                            % (storm.hung, storm.unattributed))
        if st.error or not st.poison_rejected \
                or not st.incumbent_bitwise_ok:
            failures.append("storm: error=%r poison_rejected=%s "
                            "bitwise_ok=%s" % (st.error,
                                               st.poison_rejected,
                                               st.incumbent_bitwise_ok))
        if not st.committed:
            failures.append("storm landed 0 swaps — nothing was "
                            "stress-tested")
        storm_rec = {"p99_ms": round(storm.p99_ms, 3),
                     "bound_ms": round(bound_ms, 3),
                     "promotions": storm.promotions,
                     "rollbacks": storm.rollbacks,
                     "versions": storm.versions,
                     "committed": st.committed}
    batcher.close()

    # -- the closed-loop contracts
    ledger = read_promotions(daemon.ledger_path)
    promoted = [e for e in ledger if e["event"] == "promoted"]
    if args.chaos != "loss_bomb" and not promoted:
        failures.append("no candidate survived the gauntlet in a clean "
                        "run")
    if eng.recompile_count:
        failures.append("%d post-warmup recompile(s)"
                        % eng.recompile_count)
    for rep in (cap, live):
        if rep.hung:
            failures.append("%d hung future(s)" % rep.hung)
        if rep.unattributed:
            failures.append("%d unattributed row(s)" % rep.unattributed)

    rec = {"metric": "flywheel", "value": len(promoted),
           "unit": "promotions", "chaos": args.chaos,
           "trained_steps": train_out.get("final_step"),
           "train_rollbacks": train_out.get("rollbacks"),
           "promoted": [e["step"] for e in promoted],
           "quarantined": [(e["step"], e["stage"]) for e in ledger
                           if e["event"] == "quarantined"],
           "serving_version": eng.params_version,
           "serving_rollbacks": eng.rollback_count,
           "recompiles": eng.recompile_count,
           "live_versions": live.versions,
           "live_promotions": live.promotions,
           "ledger": daemon.ledger_path,
           "failures": failures}
    if storm_rec is not None:
        rec["swap_storm"] = storm_rec
    print(json.dumps(rec), flush=True)
    if failures:
        log("FAIL: " + "; ".join(failures))
        return 1
    log("ok — %d promotion(s) through the full gauntlet, %d quarantined, "
        "0 recompiles" % (len(promoted), daemon.quarantined_count))
    return 0


if __name__ == "__main__":
    sys.exit(main())
