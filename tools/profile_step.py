#!/usr/bin/env python
"""Profile the fused ResNet-50 train step on the TPU and print a per-op
time breakdown (the `jax.profiler` trace -> xplane -> hlo_stats path).

Answers "where do the 115 ms go?" for the north-star push: groups HLO ops
by category (conv, fusion kinds, all-reduce, copy, ...) and prints the
top individual ops.  Writes the raw trace under .profile/ (git-ignored)
and the summary to stdout; `--doc` rewrites docs/PERF.md.

Reference analog: MXNet's profiler dump of per-op GPU lanes
(src/profiler/profiler.cc); here XLA gives one fused program so the
interesting unit is the HLO fusion, not the framework op.
"""
import argparse
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_step(batch, image_size=224, compute_dtype="bfloat16"):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel import make_train_step

    mx.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Xavier())
    net.shape_init((1, 3, image_size, image_size))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = make_train_step(net, loss_fn, optimizer="sgd", learning_rate=0.1,
                           momentum=0.9, wd=1e-4, compute_dtype=compute_dtype)
    x = nd.random.uniform(shape=(batch, 3, image_size, image_size))
    import numpy as np
    y = nd.array(np.random.randint(0, 1000, batch).astype(np.float32))
    return step, x, y


def capture(step, x, y, logdir, iters=5):
    import jax

    t = step.aot_compile(x, y)
    print("trace %.1fs compile %.1fs" % (t["trace"], t["compile"]),
          file=sys.stderr)
    loss = step(x, y)
    loss.wait_to_read()
    with jax.profiler.trace(logdir):
        for _ in range(iters):
            loss = step(x, y)
        loss.wait_to_read()


def find_xplane(logdir):
    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise SystemExit("no xplane.pb under %s" % logdir)
    return max(paths, key=os.path.getmtime)


def hlo_stats(xplane_path):
    """Parse the xplane with tensorboard_plugin_profile into per-HLO rows."""
    from xprof.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [xplane_path], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    obj = json.loads(data)
    return obj


def categorize(name, category):
    n = name.lower()
    c = (category or "").lower()
    if "convolution" in c or n.startswith("%convolution") or "conv" in c:
        return "convolution"
    if "all-reduce" in n or "allreduce" in c:
        return "all-reduce"
    if c:
        return c
    return "other"


def summarize(obj, total_steps):
    # hlo_stats JSON: {"p": cols meta, "d"/rows}; format is a GViz table.
    cols = [c.get("label") or c.get("id") for c in obj["cols"]]
    rows = [[(cell or {}).get("v") for cell in r["c"]] for r in obj["rows"]]

    def col(label_sub):
        for i, c in enumerate(cols):
            if label_sub.lower() in str(c).lower():
                return i
        return None

    i_cat = col("category")
    i_name = col("HLO op name") or col("hlo op")
    i_time = col("Total time") or col("occurrences")  # fallback probed later
    # prefer self time in us
    for cand in ("Total self time (us)", "total self time"):
        j = col(cand)
        if j is not None:
            i_time = j
            break
    by_cat = defaultdict(float)
    by_op = defaultdict(float)
    total = 0.0
    for r in rows:
        t = float(r[i_time] or 0.0)
        cat = categorize(str(r[i_name]), str(r[i_cat]) if i_cat is not None
                         else "")
        by_cat[cat] += t
        by_op[str(r[i_name])[:110]] += t
        total += t
    return cols, by_cat, by_op, total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--xplane", help="skip capture; parse this xplane.pb")
    ap.add_argument("--out", help="also write the hlo_stats category "
                                  "breakdown as JSON to this path — the "
                                  "measured ground truth graftcost's "
                                  "fusion heuristics diff against "
                                  "(analysis/cost_model.py)")
    args = ap.parse_args()

    if args.xplane:
        xp = args.xplane
    else:
        import jax

        cache = os.path.join(REPO, ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        logdir = os.path.join(REPO, ".profile",
                              time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(logdir, exist_ok=True)
        step, x, y = build_step(args.batch, compute_dtype=args.dtype)
        capture(step, x, y, logdir, iters=args.iters)
        xp = find_xplane(logdir)
        print("xplane: %s" % xp, file=sys.stderr)

    obj = hlo_stats(xp)
    cols, by_cat, by_op, total = summarize(obj, args.iters)
    print("== columns: %s" % cols, file=sys.stderr)
    per_step_us = total / args.iters
    print("\n== by category (total self time, %d steps) ==" % args.iters)
    for cat, t in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print("  %-28s %10.0f us  (%5.1f%%)  %7.2f ms/step"
              % (cat, t, 100 * t / total, t / args.iters / 1e3))
    print("  %-28s %10.0f us            %7.2f ms/step"
          % ("TOTAL", total, per_step_us / 1e3))
    print("\n== top %d ops ==" % args.top)
    for name, t in sorted(by_op.items(), key=lambda kv: -kv[1])[:args.top]:
        print("  %7.2f ms/step  %5.1f%%  %s"
              % (t / args.iters / 1e3, 100 * t / total, name))

    if args.out:
        # machine-readable category breakdown: the measured counterpart
        # of graftcost's predicted CostReport categories (same
        # "category -> time" shape PERF.md tables use), so the cost
        # model's fusion heuristics can be diffed against reality
        payload = {
            "version": 1,
            "tool": "profile_step",
            "iters": args.iters,
            "batch": args.batch,
            "dtype": args.dtype,
            "xplane": xp,
            "total_self_us": total,
            "per_step_ms": round(per_step_us / 1e3, 3),
            "categories": {
                cat: {"total_self_us": round(t, 1),
                      "ms_per_step": round(t / args.iters / 1e3, 3),
                      "fraction": round(t / total, 4) if total else 0.0}
                for cat, t in sorted(by_cat.items(),
                                     key=lambda kv: -kv[1])},
            "top_ops": [
                {"name": name, "ms_per_step":
                 round(t / args.iters / 1e3, 3)}
                for name, t in sorted(by_op.items(),
                                      key=lambda kv: -kv[1])[:args.top]],
        }
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, args.out)
        print("wrote %s" % args.out, file=sys.stderr)


if __name__ == "__main__":
    main()
