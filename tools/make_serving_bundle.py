#!/usr/bin/env python
"""Self-contained serving bundle (the amalgamation analog).

The reference's ``amalgamation/`` squashes a predict-only runtime into a
single C++ file so a model can be served with no MXNet checkout.  The
TPU-native runtime is Python/JAX, so the equivalent deliverable is a
directory that serves a saved model with NOTHING from the repo on the
path:

    bundle/
      libmxtpu_capi.so      the C ABI (MXPred* serving surface)
      incubator_mxnet_tpu/  the runtime package (pruned: no tests)
      model-symbol.json     the model graph
      model-0000.params     the weights
      serve.py              minimal example consumer (ctypes, MXPred*)
      README.md             how to run from anywhere

Usage:
    python tools/make_serving_bundle.py <model_prefix> <outdir> \
        [input_shape_json]          # e.g. '[1, 3, 224, 224]' 

Verify (from any cwd, repo not on path):
    cd <outdir> && python serve.py
"""
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SERVE = '''#!/usr/bin/env python
"""Minimal MXPred* consumer running entirely out of this bundle."""
import ctypes
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)                  # bundled runtime package
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

lib = ctypes.CDLL(os.path.join(HERE, "libmxtpu_capi.so"))
lib.MXGetLastError.restype = ctypes.c_char_p


def check(rc):
    assert rc == 0, lib.MXGetLastError().decode()


symbol_json = open(os.path.join(HERE, "model-symbol.json")).read()
params = open(os.path.join(HERE, "model-0000.params"), "rb").read()
shape = json.loads(os.environ.get("INPUT_SHAPE", "__DEFAULT_SHAPE__"))

h = ctypes.c_void_p()
indptr = (ctypes.c_uint32 * 2)(0, len(shape))
sdata = (ctypes.c_uint32 * len(shape))(*shape)
keys = (ctypes.c_char_p * 1)(b"data")
check(lib.MXPredCreate(symbol_json.encode(), params, len(params), 1, 0,
                       1, keys, indptr, sdata, ctypes.byref(h)))
x = np.random.RandomState(0).uniform(size=shape).astype(np.float32)
check(lib.MXPredSetInput(h, b"data", x.ctypes.data_as(
    ctypes.POINTER(ctypes.c_float)), x.size))
check(lib.MXPredForward(h))
pshape = ctypes.POINTER(ctypes.c_uint32)()
ndim = ctypes.c_uint32()
check(lib.MXPredGetOutputShape(h, 0, ctypes.byref(pshape),
                               ctypes.byref(ndim)))
oshape = [pshape[i] for i in range(ndim.value)]
out = np.zeros(int(np.prod(oshape)), np.float32)
check(lib.MXPredGetOutput(h, 0, out.ctypes.data_as(
    ctypes.POINTER(ctypes.c_float)), out.size))
check(lib.MXPredFree(h))
print("output shape:", oshape)
print("output[:5]:", out[:5])
print("SERVE OK")
'''

_README = '''# Serving bundle

Self-contained predict-only artifact (the reference `amalgamation/`
analog): everything needed to serve `model-symbol.json` +
`model-0000.params` through the MXPred* C ABI lives in this directory.

Run the bundled example consumer (CPU):

    python serve.py

Embed in your own process: load `libmxtpu_capi.so`, use the MXPred*
functions declared in the reference `c_predict_api.h` contract.  The
.so embeds CPython and imports the bundled `incubator_mxnet_tpu/`
package from this directory (set PYTHONPATH here when embedding from
C/C++).
'''


def main():
    if len(sys.argv) not in (3, 4):
        print(__doc__)
        return 1
    prefix, outdir = sys.argv[1], sys.argv[2]
    default_shape = sys.argv[3] if len(sys.argv) == 4 else "[1, 3, 224, 224]"
    os.makedirs(outdir, exist_ok=True)
    shutil.copy2(os.path.join(REPO, "src", "native", "libmxtpu_capi.so"),
                 outdir)
    for native in ("libmxtpu_native.so", "libsample_custom_op.so"):
        srcp = os.path.join(REPO, "src", "native", native)
        if os.path.exists(srcp):
            shutil.copy2(srcp, outdir)
    shutil.copy2(prefix + "-symbol.json",
                 os.path.join(outdir, "model-symbol.json"))
    shutil.copy2(prefix + "-0000.params",
                 os.path.join(outdir, "model-0000.params"))
    pkg_dst = os.path.join(outdir, "incubator_mxnet_tpu")
    if os.path.exists(pkg_dst):
        shutil.rmtree(pkg_dst)
    shutil.copytree(os.path.join(REPO, "incubator_mxnet_tpu"), pkg_dst,
                    ignore=shutil.ignore_patterns("__pycache__", "*.pyc"))
    with open(os.path.join(outdir, "serve.py"), "w") as f:
        f.write(_SERVE.replace("__DEFAULT_SHAPE__", default_shape))
    with open(os.path.join(outdir, "README.md"), "w") as f:
        f.write(_README)
    size = sum(os.path.getsize(os.path.join(dp, fn))
               for dp, _, fns in os.walk(outdir) for fn in fns)
    print("bundle at %s (%.1f MB)" % (outdir, size / 1e6))
    return 0


if __name__ == "__main__":
    sys.exit(main())
