#!/usr/bin/env python
"""Environment diagnostic (reference: tools/diagnose.py — python/pip/
library/hardware/network checks for bug reports).  TPU-native version:
python + package + jax/backend + device + feature + config report; the
network section probes the TPU tunnel instead of package mirrors (this
environment has no egress).

Usage: python tools/diagnose.py [--probe-backend]
"""
import argparse
import os
import platform
import socket
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def check_hardware():
    print("----------Hardware Info----------")
    print("Machine      :", platform.machine())
    print("Platform     :", platform.platform())
    print("Processor    :", platform.processor() or "?")
    print("CPU cores    :", os.cpu_count())
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith(("MemTotal", "MemAvailable")):
                    print(line.strip())
    except OSError:
        pass


def check_package():
    print("----------Framework Info----------")
    import incubator_mxnet_tpu as mx
    print("Version      :", getattr(mx, "__version__", "?"))
    print("Location     :", os.path.dirname(mx.__file__))
    from incubator_mxnet_tpu.runtime import feature_list
    feats = [f.name for f in feature_list() if f.enabled]
    print("Features     :", ", ".join(feats) if feats else "-")
    from incubator_mxnet_tpu import config
    print("Config vars  : %d declared MXNET_* variables" % len(config.VARS))
    for name in sorted(config.VARS):
        if os.environ.get(name) is not None:
            print("Env          : %s=%s" % (name, os.environ[name]))


def check_jax(probe_backend, user_platforms):
    print("----------JAX Info----------")
    import jax
    print("jax          :", jax.__version__)
    import jaxlib
    print("jaxlib       :", jaxlib.__version__)
    # the user's ORIGINAL env, not the cpu pin main() injects
    print("JAX_PLATFORMS:", "<unset>" if user_platforms is None
          else user_platforms)
    if probe_backend:
        t0 = time.time()
        try:
            devs = jax.devices()
            print("Devices      : %s (init %.1fs)" % (devs,
                                                      time.time() - t0))
        except Exception as e:  # noqa: BLE001
            print("Devices      : backend init FAILED: %r" % e)
    else:
        print("Devices      : (skipped; pass --probe-backend — a dead "
              "TPU tunnel hangs the probe for minutes)")


def check_tunnel(port=8083, timeout=5):
    print("----------TPU Tunnel----------")
    t0 = time.time()
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
        s.close()
        print("Port %d    : OPEN (%.2fs)" % (port, time.time() - t0))
    except OSError as e:
        print("Port %d    : unreachable (%r) — chip measurements are "
              "blocked; see tools/chip_queue.sh" % (port, e))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe-backend", action="store_true",
                    help="actually initialize the jax backend (slow / "
                         "hangs if the TPU tunnel is down)")
    args = ap.parse_args()
    if "_MXTPU_DIAG_ORIG" in os.environ:
        user_platforms = os.environ["_MXTPU_DIAG_ORIG"] or None
    else:
        user_platforms = os.environ.get("JAX_PLATFORMS")
        if not args.probe_backend and user_platforms != "cpu":
            # without --probe-backend this tool must NEVER touch a real
            # backend (a dead TPU tunnel hangs the probe for minutes),
            # but sitecustomize hooks backend selection at interpreter
            # startup — so re-exec with a cpu env pin, remembering the
            # user's original setting for the report
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["_MXTPU_DIAG_ORIG"] = user_platforms or ""
            os.execv(sys.executable, [sys.executable] + sys.argv)
    check_python()
    check_hardware()
    check_tunnel()
    check_package()
    check_jax(args.probe_backend, user_platforms)


if __name__ == "__main__":
    main()
