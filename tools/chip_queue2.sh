#!/bin/bash
# Round-4 perf-variant backlog: the roofline argument (docs/PERF.md) says
# the stock-BN byte count caps the chip at ~2.2k img/s; these runs measure
# the levers (fused ghost-BN Pallas kernels, space-to-depth stem, the new
# shifted-window max-pool backward) and re-warm the default cache.
# Probe first:  curl -m5 127.0.0.1:8083 >/dev/null && bash tools/chip_queue2.sh
set -u
cd "$(dirname "$0")/.."
LOG=${1:-chip_queue2_results.txt}
{
echo "== chip queue2 $(date -u +%FT%TZ) =="

echo "-- 1. default config (re-warm cache after maxpool-bwd change)"
timeout 580 python bench.py --chunks 3

echo "-- 2. ghost-bn 64"
timeout 580 python bench.py --chunks 3 --ghost-bn 64

echo "-- 3. ghost-bn 64 + s2d stem"
timeout 580 python bench.py --chunks 3 --ghost-bn 64 --s2d-stem

echo "-- 4. ghost-bn 32 + s2d stem"
timeout 580 python bench.py --chunks 3 --ghost-bn 32 --s2d-stem

echo "-- 5. batch 512 ghost-bn 64 + s2d"
timeout 580 python bench.py --chunks 3 --batch 512 --ghost-bn 64 --s2d-stem

echo "-- 6. int8 inference (carried over from queue1 outage)"
timeout 580 python bench.py --mode infer-int8

echo "-- 7. attention (carried over)"
timeout 580 python bench.py --mode attention

echo "-- 8. recordio-fed training (carried over)"
timeout 580 python bench.py --data recordio --record-format .npy --chunks 3

echo "== done $(date -u +%FT%TZ) =="
} 2>&1 | tee "$LOG"
