#!/usr/bin/env python
"""autotune CLI — search-based tuner on graftcost + the compile cache.

Closes the graftcost loop (``analysis/autotune.py``, docs/PERF.md
§Autotuning): enumerates the knob space for a target workload, ranks
every candidate by the trace-time CostReport roofline, eagerly rejects
GL201-infeasible configs with ZERO compiles spent, measures only the
top-K on the real backend (each compile routed through the persistent
compile cache, ``MXTPU_COMPILE_CACHE``), fits a learned residual on
predicted-vs-measured drift and re-ranks the remainder.  Emits a JSON
tuning log accounting for 100 % of candidates and a winner config
consumable by ``bench.py`` / ``Trainer.make_fused_step``.

When no TPU is reachable the measurements are *relative* CPU-mesh
numbers: the log is stamped ``backend`` / ``tpu_unavailable`` /
``relative_only`` — never silent zeros (the BENCH r04/r05 failure
mode).

Exit status: 0 — winner found; 1 — every candidate infeasible/invalid
(nothing measurable); 2 — usage errors.

Usage::

    python tools/autotune.py --target train --model dense --mesh dp=8 \
        --budget-compiles 5 --format json --out tuning.json \
        --winner-out winner.json
    python tools/autotune.py --target serve --budget-compiles 3
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _range_arg(s):
    """argparse type for --input-range (shared grammar:
    analysis.value_range.parse_range_arg)."""
    from incubator_mxnet_tpu.analysis.value_range import parse_range_arg

    try:
        return parse_range_arg(s)
    except ValueError as e:
        raise argparse.ArgumentTypeError("--input-range %s" % e)


def _parse_mesh(spec):
    axes = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        if not size:
            raise SystemExit("--mesh entries are axis=size, got %r" % part)
        axes[name.strip()] = int(size)
    return axes


def _parse_bytes(s):
    if s is None:
        return None
    units = {"kib": 2**10, "mib": 2**20, "gib": 2**30, "tib": 2**40,
             "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12, "b": 1}
    low = str(s).strip().lower()
    for u in sorted(units, key=len, reverse=True):
        if low.endswith(u):
            return float(low[: -len(u)]) * units[u]
    return float(s)


def _conv_bn_workload():
    """The graftcost-CLI conv-bn net as an autotune workload."""
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.gluon import nn

    def make_net(knobs):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(16, 3, padding=1, in_channels=3))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2D(16, 3, padding=1, in_channels=16))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 3, 16, 16)))
        return net

    def make_batch(knobs):
        rng = np.random.RandomState(0)
        b = int(knobs.get("batch", 16))
        x = nd.array(rng.rand(b, 3, 16, 16).astype(np.float32))
        y = nd.array(rng.rand(b, 16, 16, 16).astype(np.float32))
        return x, y

    return make_net, make_batch, gluon.loss.L2Loss()


def _resnet50_workload(image_size=224, classes=1000):
    """The headline bench workload (heavy — measured legs want a TPU)."""
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    def make_net(knobs):
        mx.random.seed(0)
        net = vision.resnet50_v1(classes=classes,
                                 ghost_bn=int(knobs.get("bn_group", 0)))
        net.initialize(init=mx.init.Xavier())
        net.shape_init((1, 3, image_size, image_size))
        return net

    def make_batch(knobs):
        rng = np.random.RandomState(0)
        b = int(knobs.get("batch", 32))
        x = nd.array(rng.rand(b, 3, image_size, image_size)
                     .astype(np.float32))
        y = nd.array(rng.randint(0, classes, b).astype(np.float32))
        return x, y

    return make_net, make_batch, gluon.loss.SoftmaxCrossEntropyLoss()


def _format_table(res):
    lines = ["autotune[%s] backend=%s%s — %d candidates, %d measured "
             "(%d compiles), %.1fs"
             % (res.target, res.backend,
                " (TPU UNAVAILABLE: relative numbers)"
                if res.tpu_unavailable else "",
                len(res.candidates),
                sum(1 for c in res.candidates if c.status == "measured"),
                res.compiles_spent, res.wall_s),
             "%-10s %14s %14s %14s  %s"
             % ("status", "pred s/sample", "corr s/sample",
                "meas s/sample", "knobs")]
    for c in sorted(res.candidates,
                    key=lambda c: (c.measured_sps
                                   if c.measured_sps is not None
                                   else float("inf"),
                                   c.pred_sps if c.pred_sps is not None
                                   else float("inf"))):
        def fmt(v):
            return "%.3e" % v if v is not None else "-"

        def show(k, v):
            if k in ("batch", "zero"):
                return True
            if k == "num_micro":
                return v > 1
            if k == "passes":
                return bool(v)
            if k == "schedule":
                return False  # the hash stands in for the full dict
            return v not in (None, False)

        knobs = " ".join("%s=%s" % (k, v)
                         for k, v in sorted(c.knobs.items()) if show(k, v))
        lines.append("%-10s %14s %14s %14s  %s"
                     % (c.status.replace("rejected-", "rej-"),
                        fmt(c.pred_sps), fmt(c.corrected_sps),
                        fmt(c.measured_sps), knobs))
        if c.reason:
            lines.append("           reason: %s" % c.reason[:120])
    if res.residual:
        lines.append("residual: spearman %.3f -> %.3f over %d pairs"
                     % (res.residual.get("spearman_predicted", 0.0),
                        res.residual.get("spearman_corrected", 0.0),
                        res.residual.get("n_pairs", 0)))
    if res.winner is not None:
        lines.append("winner: %s" % json.dumps(res.winner.knobs))
    else:
        best = res.best_predicted()
        if best is not None and res.budget_compiles == 0:
            lines.append("winner (predicted, budget 0): schedule_hash=%s"
                         % best.knobs.get("schedule_hash", "-"))
        else:
            lines.append("winner: NONE (no candidate was measurable)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="autotune", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--target", default="train",
                    choices=["train", "train-schedule", "serve"],
                    help="train-schedule: the graftsched per-site "
                         "search — ranks PassSchedule candidates over "
                         "--passes from ONE abstract site table "
                         "(analysis/autotune.py::"
                         "autotune_train_schedules)")
    ap.add_argument("--model", default="dense",
                    choices=["dense", "conv-bn", "resnet50"],
                    help="train-target workload; the serve target "
                         "always tunes its fixed MLP (ignores --model)")
    ap.add_argument("--mesh", default="",
                    help="mesh axes, e.g. dp=8 or dp=2,pp=4 (CPU devices "
                         "are forged off-chip)")
    ap.add_argument("--batches", default="8,16,32",
                    help="train-target batch sizes to search")
    ap.add_argument("--passes", default="",
                    help="comma-separated graftpass names (tools/"
                         "graftpass.py --list): each becomes an on/off "
                         "knob in the train search space, ranked by the "
                         "post-pass CostReport; GL201/GL301-rejected "
                         "candidates cost zero compiles.  NOTE: under "
                         "graftsched the on/off crossing is sugar for "
                         "the all-sites/no-sites schedule pair of each "
                         "pass (kept so existing tuning logs stay "
                         "comparable); per-site search is "
                         "--target train-schedule, which deprecates "
                         "this whole-program mode")
    ap.add_argument("--numerics", default="off",
                    choices=["off", "warn", "error"],
                    help="graftrange value-range gate per candidate "
                         "(analysis/value_range.py): 'error' rejects "
                         "GL4xx-infeasible configs (amp_bf16 on an "
                         "out-of-bf16-range edge, provably-overflowing "
                         "loss_scale) with zero compiles, like GL201")
    ap.add_argument("--input-range", default=None, type=_range_arg,
                    help="declared batch value range 'lo,hi' (e.g. "
                         "'0,1' for normalized images) seeding the "
                         "graftrange analysis")
    ap.add_argument("--budget-compiles", type=int, default=5,
                    help="how many candidates reach the real backend "
                         "(each costs at most one XLA compile; a warm "
                         "MXTPU_COMPILE_CACHE makes re-measures "
                         "trace-only)")
    ap.add_argument("--hbm-budget", default=None,
                    help="peak-memory budget (16GiB / 8GB / bytes) — the "
                         "GL201 eager-rejection gate")
    ap.add_argument("--device", default="cpu-proxy",
                    help="roofline device-spec registry key")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--qps", type=float, default=300.0,
                    help="serve-target offered Poisson rate")
    ap.add_argument("--requests", type=int, default=60,
                    help="serve-target requests per measured policy")
    ap.add_argument("--format", dest="fmt", default="table",
                    choices=["table", "json"])
    ap.add_argument("--out", default=None,
                    help="write the full JSON tuning log here (atomic)")
    ap.add_argument("--winner-out", default=None,
                    help="write the winner config JSON here (the shape "
                         "bench.py / Trainer.make_fused_step consume)")
    args = ap.parse_args(argv)

    mesh_axes = _parse_mesh(args.mesh)
    ndev = 1
    for v in mesh_axes.values():
        ndev *= v
    if mesh_axes and "XLA_FLAGS" not in os.environ:
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=%d" % max(ndev, 2)

    import jax

    from incubator_mxnet_tpu.analysis import DEVICE_SPECS
    from incubator_mxnet_tpu.analysis.autotune import (
        autotune_serve, autotune_train, autotune_train_schedules,
        default_train_space, dense_workload)

    if args.device not in DEVICE_SPECS:
        raise SystemExit("unknown --device %r (registry: %s)"
                         % (args.device, sorted(DEVICE_SPECS)))
    budget = _parse_bytes(args.hbm_budget)
    mesh = None
    if mesh_axes:
        from incubator_mxnet_tpu.parallel import make_mesh

        mesh = make_mesh(mesh_axes, devices=jax.devices()[:ndev])

    if args.target in ("train", "train-schedule"):
        if args.model == "dense":
            make_net, make_batch, loss_fn = dense_workload()
        elif args.model == "conv-bn":
            make_net, make_batch, loss_fn = _conv_bn_workload()
        else:
            make_net, make_batch, loss_fn = _resnet50_workload()
        pass_names = tuple(s.strip() for s in args.passes.split(",")
                           if s.strip())
        if pass_names:
            from incubator_mxnet_tpu.analysis.passes import get_pass

            for n in pass_names:
                get_pass(n)  # fail fast on unknown names
        batches = tuple(int(b) for b in args.batches.split(",") if b)
        if args.target == "train-schedule":
            if not pass_names:
                raise SystemExit("--target train-schedule needs "
                                 "--passes to build the site table")
            res = autotune_train_schedules(
                make_net, make_batch, loss_fn, passes=pass_names,
                knobs={"batch": batches[0]}, mesh=mesh,
                device=args.device, hbm_budget=budget,
                budget_compiles=args.budget_compiles,
                warmup=args.warmup, iters=args.iters,
                numerics=args.numerics, input_range=args.input_range,
                log_path=args.out)
        else:
            space = default_train_space(mesh_axes, batches=batches,
                                        passes=pass_names)
            res = autotune_train(make_net, make_batch, loss_fn,
                                 space=space,
                                 mesh=mesh, device=args.device,
                                 hbm_budget=budget,
                                 budget_compiles=args.budget_compiles,
                                 warmup=args.warmup, iters=args.iters,
                                 numerics=args.numerics,
                                 input_range=args.input_range,
                                 log_path=args.out)
    else:
        import incubator_mxnet_tpu as mx
        from incubator_mxnet_tpu import nd
        from incubator_mxnet_tpu.gluon import nn

        mx.random.seed(8)
        net = nn.HybridSequential()
        net.add(nn.Dense(64, activation="relu"), nn.Dense(16))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 32)))
        res = autotune_serve(net, (32,), mesh=mesh, device=args.device,
                             hbm_budget=budget,
                             budget_compiles=args.budget_compiles,
                             qps=args.qps, n_requests=args.requests,
                             log_path=args.out)

    if args.fmt == "json":
        print(res.to_json(indent=2))
    else:
        print(_format_table(res))

    # schedule searches at --budget-compiles 0 are pure zero-compile
    # ranking: the best PREDICTED schedule is the (hash-stamped) winner
    winner_cfg = res.winner_config()
    if args.winner_out and winner_cfg is not None:
        tmp = args.winner_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(winner_cfg, f, indent=2)
        os.replace(tmp, args.winner_out)
        print("winner config -> %s" % args.winner_out, file=sys.stderr)

    if not res.accounted():
        print("autotune: tuning log does not account for every candidate",
              file=sys.stderr)
    if res.winner is not None:
        return 0
    if args.target == "train-schedule" and args.budget_compiles == 0:
        return 0 if winner_cfg is not None else 1
    return 1


if __name__ == "__main__":
    sys.exit(main())
