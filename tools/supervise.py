#!/usr/bin/env python
"""Self-healing training CLI: launcher + watchdog + chaos matrix
(docs/RESILIENCE.md §7, ``parallel/supervisor.py``).

Two modes in one file so a respawned rank runs the exact binary the
supervisor does:

- **supervisor mode** (default): spawn ``-n`` ranks of the built-in
  supervised worker through the ``tools/launch.py`` DMLC_* env
  protocol (``DMLC_PS_ROOT_URI``/``PORT`` rendezvous,
  ``DMLC_NUM_WORKER``/``DMLC_WORKER_ID`` identity,
  ``MXNET_RESTART_COUNT`` attempt number) and drive the detection →
  ladder → resume loop until the job resolves or gives up;
- **worker mode** (``--worker``, spawned internally): the rank body —
  a small deterministic train job (the ``tests/elastic_worker.py``
  pattern: process-spanning dp mesh + zero=1 when the backend can
  compile cross-process programs, per-process replicated otherwise)
  driven by :func:`~parallel.supervisor.run_supervised` with
  heartbeats, periodic checkpoints and in-process divergence rollback.
  Chaos arms itself from the ``MXTPU_CHAOS`` env var on attempt 0
  only, so every injected failure is recoverable by restart.

``--chaos SCENARIO`` runs one scenario from the matrix
(``kill_process``, ``hang_step``, ``straggler_process``,
``host_loss_during_save``, ``loss_bomb``); ``--chaos all`` runs every
one and exits 1 if ANY scenario ends unrecovered, misses a required
health-ledger event, exceeds the MTTR bound, or leaves a torn
checkpoint visible — the ``serve_bench --chaos`` discipline for the
training tier.  ``--format json`` emits one JSON record per scenario.
"""
from __future__ import annotations

import argparse
import contextlib
import json
import os
import socket
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: scenario -> (chaos spec defaults, minimum width, supervisor-config
#: overrides).  ``rank`` -1 means "the last rank" (keeps rank 0 — the
#: checkpoint-commit coordinator — alive in multi-rank scenarios).
SCENARIOS = {
    "kill_process": dict(spec=dict(at=3), width=1, cfg={}),
    "hang_step": dict(spec=dict(at=3, duration=600.0), width=1, cfg={}),
    "straggler_process": dict(
        spec=dict(at=4, delay=1.0), width=2,
        # the slowdown starts AFTER the coordinated step-4 save, so the
        # post-chaos phase is uncoupled (a coordinated boundary save
        # throttles every rank to the slowest peer's pace, which would
        # hide the step lag) and recovery provably resumes from the
        # committed step 4.  Verdict thresholds are loosened for the
        # short lag window, and the stall floor is RAISED so the
        # healthy rank blocking in its final save's marker wait cannot
        # trip the hang detector before the straggler verdict does.
        # sync="auto" arms the policy ladder: before the supervisor's
        # grace window escalates to a restart, every rank's step must
        # observe the straggler verdict and degrade allreduce→async
        # (a "sync_degrade" ledger event — the gated proof the ladder
        # ran), so the healthy rank keeps stepping instead of blocking
        # on the slow peer.
        args=dict(checkpoint_every=4, sync="auto",
                  straggler_factor=1.2, straggler_min_lag=2),
        cfg=dict(straggler_factor=1.2, straggler_min_lag=2,
                 straggler_grace=1.0, min_stall_timeout=8.0)),
    "host_loss_during_save": dict(spec=dict(save=1), width=2,
                                  cfg=dict(min_stall_timeout=15.0)),
    "loss_bomb": dict(spec=dict(at=4, factor=1e4), width=1, cfg={}),
}

#: the event sequence a green scenario MUST leave in the merged health
#: ledger (the missing-ledger-event gate `--chaos` exits 1 on)
REQUIRED_EVENTS = {
    "kill_process": ("launch", "fault", "restart", "recovered",
                     "resolved"),
    "hang_step": ("launch", "heartbeat_gap", "fault", "restart",
                  "recovered", "resolved"),
    "straggler_process": ("launch", "straggler", "sync_degrade", "fault",
                          "restart", "recovered", "resolved"),
    "host_loss_during_save": ("launch", "fault", "restart", "recovered",
                              "resolved"),
    "loss_bomb": ("launch", "divergence", "rollback", "recovered",
                  "done", "resolved"),
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# worker mode (the rank body)
# ---------------------------------------------------------------------------

def build_worker_job(outdir: str, checkpoint_every=2,
                     commit_timeout: float = 10.0, skip_budget=None,
                     sync: str = "allreduce", straggler_factor: float = 3.0,
                     straggler_min_lag: int = 4):
    """Build the deterministic supervised train job every rank runs —
    module-level so tests can run the IDENTICAL job in-process as the
    bit-exactness reference.  The step bound is the caller's
    (``run_supervised(until_step=)``), not the job's.  Returns
    ``(step, data_iter, manager, config, rank, nproc)``."""
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.io import NDArrayIter, ResilientIter
    from incubator_mxnet_tpu.parallel import (CheckpointManager,
                                              SupervisorConfig,
                                              distributed, make_mesh,
                                              make_train_step)
    import jax

    distributed.initialize()  # DMLC_* env; no-op at world size 1
    rank = distributed.process_index()
    nproc = distributed.process_count()
    spmd = nproc > 1 and distributed.collectives_supported()
    if spmd:
        mesh = distributed.make_process_mesh({"dp": -1})
    else:
        mesh = make_mesh({"dp": 1}, devices=jax.local_devices()[:1])

    mx.random.seed(0)
    net = nn.HybridSequential()
    for _ in range(2):
        net.add(nn.Dense(16, activation="tanh"))
    net.add(nn.Dense(13))
    net.initialize(init=mx.init.Xavier())
    net(nd.ones((2, 16)))
    if sync == "allreduce":
        step = make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                               optimizer="adam", learning_rate=0.01,
                               mesh=mesh, batch_axis="dp", zero=1,
                               lint="error",
                               skip_streak_budget=skip_budget)
    else:
        # async-capable rung (sync="async"/"auto"): one replica per
        # rank process exchanging through a ParamService, no mesh
        # collectives (docs/RESILIENCE.md §8) — the straggler chaos
        # scenario's degradation target
        step = make_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                               optimizer="adam", learning_rate=0.01,
                               sync=sync, staleness_bound=4,
                               lint="error",
                               skip_streak_budget=skip_budget)
    mgr = CheckpointManager(os.path.join(outdir, "ckpt"),
                            commit_timeout=commit_timeout)

    rngd = np.random.RandomState(5)
    X = rngd.rand(64, 16).astype(np.float32)
    Y = rngd.randint(0, 4, 64).astype(np.float32)
    np.random.seed(3)
    it = ResilientIter(NDArrayIter(X, Y, batch_size=8, shuffle=True))
    if spmd:
        # one GSPMD program spans processes: each rank feeds its row
        # slice of the global batch (the degraded replicated mode —
        # this CPU jaxlib — computes the full batch on every rank)
        lo, hi = rank * 8 // nproc, (rank + 1) * 8 // nproc
        it = _RowSlice(it, lo, hi)
    cfg = SupervisorConfig(checkpoint_every=checkpoint_every,
                           straggler_factor=straggler_factor,
                           straggler_min_lag=straggler_min_lag)
    return step, it, mgr, cfg, rank, nproc


class _RowSlice:
    """Feed this process's row slice of each global batch (real spmd
    mode: one GSPMD program spans processes, each host supplies its
    addressable rows).  Delegates the iterator-state protocol to the
    wrapped iterator so checkpoints carry the GLOBAL stream position."""

    def __init__(self, inner, lo: int, hi: int):
        self.inner, self.lo, self.hi = inner, lo, hi

    def next(self):
        import numpy as np

        from incubator_mxnet_tpu import nd

        b = self.inner.next()
        b.data = [nd.array(np.ascontiguousarray(
            d.asnumpy()[self.lo:self.hi])) for d in b.data]
        b.label = [nd.array(np.ascontiguousarray(
            v.asnumpy()[self.lo:self.hi])) for v in b.label]
        return b

    def reset(self):
        self.inner.reset()

    def close(self):
        self.inner.close()

    def state_dict(self):
        return self.inner.state_dict()

    def load_state_dict(self, state):
        self.inner.load_state_dict(state)


def _parse_chaos(spec: str):
    """``"name:k=v,k=v"`` → ``(name, {k: float-or-int})``."""
    name, _, rest = spec.partition(":")
    kw = {}
    for part in filter(None, rest.split(",")):
        k, _, v = part.partition("=")
        kw[k] = float(v) if ("." in v or "e" in v.lower()) else int(v)
    return name, kw


@contextlib.contextmanager
def _die_at_step(at: int):
    """SIGKILL this process right before supervised step call ``at``
    (0-based) — the kill_process scenario through the same
    ``supervisor._run_step`` choke point the other injectors use."""
    from incubator_mxnet_tpu.parallel import fault_injection as fi
    from incubator_mxnet_tpu.parallel import supervisor as sup

    real = sup._run_step
    state = {"seen": 0}

    def lethal(step, x, y):
        i = state["seen"]
        state["seen"] += 1
        if i == at:
            fi.kill_process()
        return real(step, x, y)

    sup._run_step = lethal
    try:
        yield
    finally:
        sup._run_step = real


@contextlib.contextmanager
def _die_during_save(save_index: int):
    """Arm ``fault_injection.host_loss_during_save`` on the
    ``save_index``-th boundary save (0-based): the process dies on the
    FIRST file write inside that save, leaving a torn stage the commit
    protocol must never publish."""
    from incubator_mxnet_tpu.parallel import fault_injection as fi
    from incubator_mxnet_tpu.parallel import supervisor as sup

    real = sup._save_checkpoint
    state = {"seen": 0}

    def lethal(step, mgr, it):
        i = state["seen"]
        state["seen"] += 1
        if i == save_index:
            with fi.host_loss_during_save(at=0):
                return real(step, mgr, it)
        return real(step, mgr, it)

    sup._save_checkpoint = lethal
    try:
        yield
    finally:
        sup._save_checkpoint = real


def _chaos_context(name: str, kw: dict):
    from incubator_mxnet_tpu.parallel import fault_injection as fi

    if name == "kill_process":
        return _die_at_step(int(kw.get("at", 3)))
    if name == "hang_step":
        return fi.hang_step(at=int(kw.get("at", 3)),
                            duration=float(kw.get("duration", 600.0)))
    if name == "straggler_process":
        # a per-step slowdown = a long run of short wedges
        return fi.hang_step(at=int(kw.get("at", 4)),
                            duration=float(kw.get("delay", 1.0)),
                            count=10 ** 6)
    if name == "host_loss_during_save":
        return _die_during_save(int(kw.get("save", 1)))
    if name == "loss_bomb":
        return fi.loss_bomb(at=int(kw.get("at", 4)),
                            factor=float(kw.get("factor", 1e4)))
    raise SystemExit("unknown chaos scenario %r (known: %s)"
                     % (name, ", ".join(sorted(SCENARIOS))))


def worker_main(args) -> int:
    # each rank must be a 1-device host: the parent (or a test
    # process) may force a virtual multi-device CPU via XLA_FLAGS
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from incubator_mxnet_tpu.parallel.supervisor import (EXIT_DIVERGED,
                                                         DivergenceError,
                                                         run_supervised)

    step, it, mgr, cfg, rank, nproc = build_worker_job(
        args.dir, checkpoint_every=args.checkpoint_every,
        commit_timeout=args.commit_timeout,
        sync=getattr(args, "sync", "allreduce"),
        straggler_factor=getattr(args, "straggler_factor", 3.0),
        straggler_min_lag=getattr(args, "straggler_min_lag", 4))
    attempt = int(os.environ.get("MXNET_RESTART_COUNT", "0"))
    chaos_env = os.environ.get("MXTPU_CHAOS", "")
    stack = contextlib.ExitStack()
    if chaos_env and attempt == 0:
        name, kw = _parse_chaos(chaos_env)
        victim = int(kw.pop("rank", nproc - 1))
        if victim < 0:
            victim += nproc
        if rank == victim:
            stack.enter_context(_chaos_context(name, kw))

    def dump(payload):
        path = os.path.join(args.dir, "result_rank%d.json" % rank)
        with open(path + ".tmp", "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(path + ".tmp", path)

    with stack:
        try:
            res = run_supervised(step, it, mgr, until_step=args.steps,
                                 config=cfg, rank=rank)
        except DivergenceError as e:
            dump({"rank": rank, "attempt": attempt, "status": "diverged",
                  "error": str(e)})
            return EXIT_DIVERGED
    it.close()
    dump({"rank": rank, "attempt": attempt, "status": "done",
          "width": nproc, **res})
    print("supervised worker done (rank %d/%d, attempt %d, step %d, "
          "%d rollbacks)" % (rank, nproc, attempt, res["final_step"],
                             res["rollbacks"]), flush=True)
    return 0


# ---------------------------------------------------------------------------
# supervisor mode
# ---------------------------------------------------------------------------

def make_launcher(args, chaos_spec: str = ""):
    """A ``Supervisor``-shaped ``launch(width, attempt)`` spawning
    worker-mode interpreters of THIS file under the ``tools/launch.py``
    env protocol, on a fresh rendezvous port per attempt."""
    me = os.path.abspath(__file__)

    def launch(width, attempt):
        port = _free_port()
        procs = []
        for rank in range(width):
            env = dict(os.environ)
            env.update({
                "DMLC_ROLE": "worker",
                "DMLC_PS_ROOT_URI": "127.0.0.1",
                "DMLC_PS_ROOT_PORT": str(port),
                "DMLC_NUM_WORKER": str(width),
                "DMLC_NUM_SERVER": "0",
                "DMLC_WORKER_ID": str(rank),
                "MXNET_RESTART_COUNT": str(attempt),
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": _REPO + os.pathsep
                + env.get("PYTHONPATH", ""),
            })
            if chaos_spec:
                env["MXTPU_CHAOS"] = chaos_spec
            cmd = [sys.executable, me, "--worker", "--dir", args.dir,
                   "--steps", str(args.steps),
                   "--checkpoint-every", str(args.checkpoint_every),
                   "--commit-timeout", str(args.commit_timeout),
                   "--sync", getattr(args, "sync", "allreduce"),
                   "--straggler-factor",
                   str(getattr(args, "straggler_factor", 3.0)),
                   "--straggler-min-lag",
                   str(getattr(args, "straggler_min_lag", 4))]
            procs.append(subprocess.Popen(cmd, env=env))
        return procs

    return launch


def make_config(args, overrides: dict = ()):
    from incubator_mxnet_tpu.parallel import SupervisorConfig

    kw = dict(max_restarts=args.max_restarts,
              min_stall_timeout=args.min_stall,
              startup_timeout=args.startup_timeout,
              backoff=args.backoff,
              checkpoint_every=args.checkpoint_every)
    kw.update(dict(overrides or {}))
    return SupervisorConfig(**kw)


def torn_visible(ckpt_dir: str) -> int:
    """Committed-looking step dirs whose manifest is missing or
    unparseable — the count of torn checkpoints VISIBLE to a restore
    (must always be 0: ``.tmp-step-*`` staging debris is fine, a torn
    ``step-*`` dir is a broken commit protocol)."""
    n = 0
    if not os.path.isdir(ckpt_dir):
        return 0
    for name in os.listdir(ckpt_dir):
        if not name.startswith("step-"):
            continue
        try:
            with open(os.path.join(ckpt_dir, name, "manifest.json")) as f:
                json.load(f)
        except (OSError, ValueError):
            n += 1
    return n


def supervise_once(args, chaos_spec: str = "", cfg_overrides=()) -> dict:
    from incubator_mxnet_tpu.parallel import Supervisor
    from incubator_mxnet_tpu.parallel.supervisor import read_ledger

    # heartbeats, per-rank ledgers and committed steps all live in the
    # CHECKPOINT dir (next to what they describe) — watch that
    ckpt_dir = os.path.join(args.dir, "ckpt")
    sup = Supervisor(make_launcher(args, chaos_spec), width=args.n,
                     directory=ckpt_dir, config=make_config(
                         args, cfg_overrides))
    out = sup.run(timeout=args.timeout)
    events = read_ledger(ckpt_dir)
    out["events"] = [e["event"] for e in events]
    out["mttrs"] = sorted(set(out.get("mttrs", []))
                          | {float(e["mttr"]) for e in events
                             if e["event"] == "recovered"
                             and "mttr" in e})
    out["torn_visible"] = torn_visible(os.path.join(args.dir, "ckpt"))
    return out


def run_chaos(scenario: str, args, fmt: str) -> dict:
    info = SCENARIOS[scenario]
    spec = scenario + ":" + ",".join(
        "%s=%s" % (k, v) for k, v in info["spec"].items())
    sub = argparse.Namespace(**vars(args))
    sub.n = max(args.n, info["width"])
    sub.dir = os.path.join(args.dir, scenario)
    for k, v in info.get("args", {}).items():
        setattr(sub, k, v)
    os.makedirs(sub.dir, exist_ok=True)
    out = supervise_once(sub, chaos_spec=spec,
                         cfg_overrides=info["cfg"])
    missing = [ev for ev in REQUIRED_EVENTS[scenario]
               if ev not in out["events"]]
    mttr = max(out["mttrs"], default=None)
    ok = (out["outcome"] == "resolved" and not missing
          and out["torn_visible"] == 0
          and mttr is not None and mttr <= args.mttr_bound)
    rec = {"scenario": scenario, "ok": ok, "outcome": out["outcome"],
           "restarts": out["restarts"], "shrinks": out["shrinks"],
           "mttr": mttr, "mttr_bound": args.mttr_bound,
           "missing_events": missing,
           "torn_visible": out["torn_visible"],
           "final_step": out.get("final_step"),
           "width": out["width"]}
    if fmt == "json":
        print(json.dumps(rec, sort_keys=True), flush=True)
    else:
        print("[chaos %-22s] %s  restarts=%d shrinks=%d mttr=%s%s"
              % (scenario, "OK " if ok else "FAIL", rec["restarts"],
                 rec["shrinks"],
                 "%.2fs" % mttr if mttr is not None else "-",
                 " missing=%s" % missing if missing else ""),
              flush=True)
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Self-healing training supervisor "
                    "(docs/RESILIENCE.md §7)")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: the rank body
    ap.add_argument("-n", "--num-workers", dest="n", type=int, default=1)
    ap.add_argument("--steps", type=int, default=8,
                    help="applied steps to train to (default 8)")
    ap.add_argument("--dir", default=None,
                    help="run directory (checkpoints, heartbeats, "
                         "health ledger); default: a fresh tempdir")
    ap.add_argument("--chaos", default=None,
                    help="inject one scenario (%s) or 'all'"
                         % "|".join(sorted(SCENARIOS)))
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--sync", choices=("allreduce", "async", "auto"),
                    default="allreduce",
                    help="worker gradient-exchange rung: the fused "
                         "allreduce step, the bounded-staleness async "
                         "parameter service, or the straggler-adaptive "
                         "policy ladder between them (RESILIENCE.md §8)")
    ap.add_argument("--straggler-factor", type=float, default=3.0)
    ap.add_argument("--straggler-min-lag", type=int, default=4)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--commit-timeout", type=float, default=10.0)
    ap.add_argument("--min-stall", type=float, default=2.0,
                    help="stall-timeout floor, seconds (the EMA "
                         "auto-calibration never goes below this)")
    ap.add_argument("--startup-timeout", type=float, default=60.0)
    ap.add_argument("--backoff", type=float, default=0.25)
    ap.add_argument("--timeout", type=float, default=180.0,
                    help="bound on one supervised run, seconds")
    ap.add_argument("--mttr-bound", type=float, default=60.0,
                    help="chaos gate: max seconds from fault detection "
                         "to training resumed")
    args = ap.parse_args(argv)
    if args.worker:
        if not args.dir:
            ap.error("--worker requires --dir")
        return worker_main(args)
    if args.dir is None:
        args.dir = tempfile.mkdtemp(prefix="mxtpu_supervise_")
        print("run dir: %s" % args.dir, file=sys.stderr, flush=True)

    if args.chaos:
        names = sorted(SCENARIOS) if args.chaos == "all" else \
            [s.strip() for s in args.chaos.split(",")]
        unknown = [s for s in names if s not in SCENARIOS]
        if unknown:
            ap.error("unknown chaos scenario(s) %s (known: %s)"
                     % (unknown, ", ".join(sorted(SCENARIOS))))
        records = [run_chaos(s, args, args.format) for s in names]
        bad = [r["scenario"] for r in records if not r["ok"]]
        if args.format == "text":
            print("chaos matrix: %d/%d green%s"
                  % (len(records) - len(bad), len(records),
                     " (FAILED: %s)" % ", ".join(bad) if bad else ""),
                  flush=True)
        return 1 if bad else 0

    out = supervise_once(args)
    if args.format == "json":
        print(json.dumps(out, sort_keys=True, default=str), flush=True)
    else:
        print("supervise: %s (width %d, %d restarts, %d shrinks, "
              "final step %s)" % (out["outcome"], out["width"],
                                  out["restarts"], out["shrinks"],
                                  out.get("final_step")), flush=True)
    return 0 if out["outcome"] == "resolved" else 1


if __name__ == "__main__":
    sys.exit(main())
