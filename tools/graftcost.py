#!/usr/bin/env python
"""graftcost CLI — trace-time cost report for a model + mesh + knob set.

Builds the requested model, constructs the fused train step with the
given parallelism knobs, and costs its traced program WITHOUT compiling
or running a step (``analysis/cost_model.py``; catalog and field
reference in docs/ANALYSIS.md): per-category FLOPs / fusion-aware HBM
bytes, peak live-buffer memory (donation-, remat- and ZeRO-sharding-
aware), per-mesh-axis collective volume, and the roofline step-time
estimate for a registry device (``tpu-v5e`` default, ``cpu-proxy`` for
off-chip relative numbers).

Exit status 1 when any error-severity GL2xx diagnostic fires — with
``--hbm-budget`` this is the eager infeasibility gate (GL201) the
autotuner (ROADMAP item 4) uses to reject configs before paying a
compile.

``--diff profile.json`` diffs the prediction against the measured
category breakdown ``tools/profile_step.py --out`` writes: a
per-category predicted/measured/drift table (the standalone form of
the autotuner's residual-fit input).  Measured hlo_stats categories
are folded into the prediction's category space (fusion kinds →
elementwise, all-reduce/-gather → collective).  Exit status 2 when the
worst per-category drift exceeds ``--drift-threshold`` (default 0.5 =
50 %).

Usage::

    python tools/graftcost.py --model dense --batch 16
    python tools/graftcost.py --model resnet50 --batch 256 --compute-dtype
        bfloat16 --format json
    python tools/graftcost.py --model dense --mesh dp=8 --zero 1
        --hbm-budget 16GiB
    python tools/graftcost.py --model resnet50 --batch 256 --compute-dtype
        bfloat16 --diff profile.json --drift-threshold 0.3
"""
from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _parse_mesh(spec):
    """'dp=8' / 'dp=2,pp=4' -> ordered dict of axis sizes."""
    axes = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        if not size:
            raise SystemExit("--mesh entries are axis=size, got %r" % part)
        axes[name.strip()] = int(size)
    return axes


def _parse_bytes(s):
    """'16GiB' / '8GB' / '1048576' -> bytes."""
    if s is None:
        return None
    s = str(s).strip()
    units = {"kib": 2**10, "mib": 2**20, "gib": 2**30, "tib": 2**40,
             "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12,
             "b": 1}
    low = s.lower()
    for u in sorted(units, key=len, reverse=True):
        if low.endswith(u):
            return float(low[: -len(u)]) * units[u]
    return float(s)


def _build_model(name, feat=16, layers=4, ghost_bn=0):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    if name == "dense":
        # the tests/test_zero_sharding.py net: 4 x Dense(16)
        net = nn.HybridSequential()
        for _ in range(layers):
            net.add(nn.Dense(feat, activation="tanh"))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, feat)))
        return net, (feat,), "dense"
    if name == "conv-bn":
        net = nn.HybridSequential()
        net.add(nn.Conv2D(16, 3, padding=1, in_channels=3))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2D(16, 3, padding=1, in_channels=16))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 3, 16, 16)))
        return net, (3, 16, 16), "conv"
    if name == "resnet50":
        from incubator_mxnet_tpu.gluon.model_zoo import vision

        # ghost_bn > 0: the fused ghost-BN perf variant (Pallas
        # kernels + GhostBN downsample branches; parallel/fused_bn.py)
        # — the round-19 byte table's fused rows come from here
        net = vision.resnet50_v1(classes=1000, ghost_bn=ghost_bn)
        net.initialize(init=mx.init.Zero())
        net.shape_init((1, 3, 224, 224))
        return net, (3, 224, 224), "conv"
    raise SystemExit("unknown --model %r (dense, conv-bn, resnet50)" % name)


#: ResNet-50 v1 BN-layer inventory: (body C, exit C, spatial, blocks)
#: per stage.  conv1 of each stage's first block carries the stride, so
#: every BN in a stage sees the same H = W = spatial.
_R50_STAGES = [
    (64, 256, 56, 3),
    (128, 512, 28, 4),
    (256, 1024, 14, 6),
    (512, 2048, 7, 3),
]


def _resnet50_kernel_plans(batch, itemsize, group):
    """Per-layer fused-BN kernel-plan table for the resnet50 workload:
    which variant (whole-L fused / lane-fold / spatial-tiled / jnp
    fallback) each distinct BN layer selects at the real VMEM budget,
    with the padded window bytes and fold factor the feasibility check
    charged.  Mirrors the model zoo's dual_out wiring: every residual
    block exit is a dual-cotangent site except the LAST stage's tail
    block (resnet.py::_make_layer)."""
    from incubator_mxnet_tpu.parallel.fused_bn import plan_describe

    rows = [("stem", 64, 112, 1, False, False, False)]
    last = len(_R50_STAGES) - 1
    for i, (bc, ec, hw, k) in enumerate(_R50_STAGES):
        s = "stage%d" % (i + 1)
        rows.append((s + ".body", bc, hw, 2 * k, False, False, False))
        rows.append((s + ".shortcut", ec, hw, 1, False, False, False))
        rows.append((s + ".exit.ds", ec, hw, 1, True, True, True))
        if i == last:
            if k > 2:
                rows.append((s + ".exit", ec, hw, k - 2, True, False,
                             True))
            rows.append((s + ".exit.tail", ec, hw, 1, True, False,
                         False))
        else:
            rows.append((s + ".exit", ec, hw, k - 1, True, False, True))
    out = []
    for layer, c, hw, count, res, donate, dual in rows:
        d = plan_describe(batch, c, hw, hw, itemsize, group, res,
                          donate, dual)
        out.append({"layer": layer, "count": count,
                    "shape": "%dx%dx%dx%d" % (batch, c, hw, hw),
                    "residual": res, "donate": donate, **d})
    return out


def _print_kernel_plans(plans, batch, itemsize, group, fmt):
    import json as _json

    if fmt == "json":
        print(_json.dumps({"version": 1, "batch": batch,
                           "itemsize": itemsize, "bn_group": group,
                           "layers": plans}, indent=2))
        return
    print("resnet50 fused ghost-BN kernel plans — batch %d, itemsize %d, "
          "bn_group %d" % (batch, itemsize, group))
    hdr = ("layer", "count", "shape", "res", "dual", "variant", "bwd",
           "fold", "l_tile", "l_tile_bwd", "window_mb")

    def cell(p, h):
        if h == "res":
            return "res+don" if p["donate"] else \
                ("res" if p["residual"] else "-")
        if h == "dual":
            return "dual" if p["dual"] else "-"
        return str(p.get(h, "-"))
    widths = [max(len(h), max((len(cell(p, h)) for p in plans),
                              default=0)) for h in hdr]
    print("  ".join("%-*s" % (w, h) for w, h in zip(widths, hdr)))
    for p in plans:
        print("  ".join("%-*s" % (w, cell(p, h))
                        for w, h in zip(widths, hdr)))


#: measured hlo_stats category (tools/profile_step.py) -> predicted
#: CostReport category.  XLA reports fused elementwise/reduction work
#: as "fusion" kinds, so those fold into elementwise — reduction time
#: inside a convert_reduce_fusion is indistinguishable in the measured
#: breakdown.  Unmatched categories fold into "other" (copies, infeed).
def _map_measured_category(name: str) -> str:
    n = str(name).lower()
    if "conv" in n:
        return "conv"
    if any(k in n for k in ("all-reduce", "allreduce", "all-gather",
                            "allgather", "reduce-scatter", "collective",
                            "all-to-all", "permute")):
        return "collective"
    if "scatter" in n or "gather" in n:
        return "scatter_gather"
    if any(k in n for k in ("fusion", "elementwise", "loop", "convert",
                            "reduce")):
        return "elementwise"
    return "other"


def _pred_category_ms(report, n_dev):
    """Per-category lower-bound milliseconds from a CostReport: each
    category's max of its compute and HBM roofline (comm handled by the
    collective row's wire bytes)."""
    sp = report.spec()
    out = {}
    for k, c in report.categories.items():
        hbm_s = c.hbm_bytes / (sp.hbm_bytes_per_s * n_dev)
        fl_s = c.flops / (sp.flops_per_s * n_dev)
        out[k] = 1e3 * max(hbm_s, fl_s)
    comm_s = max((c.wire_bytes / sp.ici_bytes_per_s
                  for c in report.comm.values()), default=0.0)
    if comm_s:
        out["collective"] = out.get("collective", 0.0) + 1e3 * comm_s
    return out


def _diff_profile(report, profile_path, threshold, fmt):
    """The --diff leg: per-category predicted vs measured ms table.
    Returns (max_abs_drift, rows) and prints; drift = (measured -
    predicted) / measured.  The measured side folds into the predicted
    category space first (elementwise absorbs reduction in BOTH: the
    fusion kinds are not separable in hlo_stats)."""
    import json as _json

    with open(profile_path) as f:
        prof = _json.load(f)
    measured = {}
    for name, row in prof.get("categories", {}).items():
        cat = _map_measured_category(name)
        measured[cat] = measured.get(cat, 0.0) + float(row["ms_per_step"])
    n_dev = max(report.n_devices, 1)
    pred = _pred_category_ms(report, n_dev)
    # reduction folds into elementwise on the predicted side too
    # (measured fusions lump them)
    pred["elementwise"] = pred.get("elementwise", 0.0) \
        + pred.pop("reduction", 0.0)
    cats = sorted(set(pred) | set(measured))
    rows = []
    worst = 0.0
    for cat in cats:
        p = pred.get(cat, 0.0)
        m = measured.get(cat, 0.0)
        if p < 0.01 and m < 0.01:  # both under 10 us: noise, not drift
            drift = 0.0
        elif m > 0:
            drift = (m - p) / m
        else:
            drift = -1.0  # predicted cost the profile never saw
        # "other" (copies, infeed) has no predicted counterpart by
        # design — report it but keep it out of the gate
        if cat != "other":
            worst = max(worst, abs(drift))
        rows.append({"category": cat, "predicted_ms": round(p, 3),
                     "measured_ms": round(m, 3),
                     "drift": round(drift, 4)})
    total_p, total_m = sum(pred.values()), sum(measured.values())
    total_drift = (total_m - total_p) / total_m if total_m > 0 else 0.0
    payload = {"version": 1, "profile": profile_path,
               "threshold": threshold, "rows": rows,
               "total": {"predicted_ms": round(total_p, 3),
                         "measured_ms": round(total_m, 3),
                         "drift": round(total_drift, 4)},
               "max_abs_drift": round(worst, 4),
               "over_threshold": worst > threshold}
    if fmt == "json":
        print(_json.dumps(payload, indent=2))
    else:
        print("%-16s %12s %12s %9s" % ("category", "pred ms", "meas ms",
                                       "drift"))
        for r in rows:
            print("%-16s %12.3f %12.3f %8.1f%%"
                  % (r["category"], r["predicted_ms"], r["measured_ms"],
                     100 * r["drift"]))
        print("%-16s %12.3f %12.3f %8.1f%%" % ("TOTAL", total_p, total_m,
                                               100 * total_drift))
        if worst > threshold:
            print("graftcost --diff: max per-category drift %.1f%% "
                  "exceeds threshold %.1f%%"
                  % (100 * worst, 100 * threshold), file=sys.stderr)
    return worst, payload


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcost", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--model", default="dense",
                    choices=["dense", "conv-bn", "resnet50"])
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--mesh", default="",
                    help="mesh axes, e.g. dp=8 or dp=2,pp=4 (devices are "
                         "CPU-forged off-chip)")
    ap.add_argument("--optimizer", default="sgd",
                    choices=["sgd", "adam"])
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--zero", type=int, default=0, choices=[0, 1])
    ap.add_argument("--multi-precision", action="store_true")
    ap.add_argument("--pipeline-stages", type=int, default=None)
    ap.add_argument("--num-micro", type=int, default=1)
    ap.add_argument("--pipeline-remat", action="store_true")
    ap.add_argument("--no-donate", action="store_true")
    ap.add_argument("--compute-dtype", default=None,
                    help="e.g. bfloat16 (default: f32)")
    ap.add_argument("--ghost-bn", "--bn-group", dest="ghost_bn", type=int,
                    default=0, metavar="GROUP",
                    help="resnet50 only: fused ghost-BN variant with "
                         "this bn_group cap (0 = stock BatchNorm) — the "
                         "PERF.md fused byte table without a chip")
    ap.add_argument("--kernel-plans", action="store_true",
                    help="resnet50 only: print the per-layer fused-BN "
                         "kernel-plan table (variant / window bytes / "
                         "fold factor per distinct BN layer at the real "
                         "VMEM budget) instead of the cost report; "
                         "honors --batch, --compute-dtype and "
                         "--ghost-bn (group defaults to the bench "
                         "workload's 16)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated graftpass names applied to the "
                         "step before costing (the autotune post-pass "
                         "analyze_cost path), e.g. "
                         "space_to_depth,maxpool_bwd_mask")
    ap.add_argument("--device", default="tpu-v5e",
                    help="roofline device-spec registry key")
    ap.add_argument("--hbm-budget", default=None,
                    help="peak-memory budget (bytes; 16GiB / 8GB forms "
                         "accepted) — GL201 errors over it, exit 1")
    ap.add_argument("--format", dest="fmt", default="table",
                    choices=["table", "json"])
    ap.add_argument("--diff", default=None, metavar="PROFILE_JSON",
                    help="diff the prediction against a measured "
                         "category breakdown written by "
                         "tools/profile_step.py --out; exit 2 when the "
                         "worst per-category drift exceeds "
                         "--drift-threshold")
    ap.add_argument("--drift-threshold", type=float, default=0.5,
                    help="--diff gate: max acceptable |measured - "
                         "predicted| / measured per category "
                         "(default 0.5)")
    args = ap.parse_args(argv)

    mesh_axes = _parse_mesh(args.mesh)
    ndev = 1
    for v in mesh_axes.values():
        ndev *= v
    if mesh_axes and "XLA_FLAGS" not in os.environ:
        # forge enough host devices for the mesh BEFORE jax initializes
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=%d" % max(ndev, 2)

    if args.kernel_plans:
        if args.model != "resnet50":
            raise SystemExit("--kernel-plans applies to --model resnet50 "
                             "only")
        import jax.numpy as _jnp

        itemsize = _jnp.dtype(args.compute_dtype or "float32").itemsize
        group = args.ghost_bn or 16
        plans = _resnet50_kernel_plans(args.batch, itemsize, group)
        _print_kernel_plans(plans, args.batch, itemsize, group, args.fmt)
        return 0

    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.analysis import DEVICE_SPECS, Severity
    from incubator_mxnet_tpu.parallel import make_train_step
    from incubator_mxnet_tpu import gluon

    if args.device not in DEVICE_SPECS:
        raise SystemExit("unknown --device %r (registry: %s)"
                         % (args.device, sorted(DEVICE_SPECS)))
    if args.ghost_bn and args.model != "resnet50":
        raise SystemExit("--ghost-bn applies to --model resnet50 only")
    net, in_shape, kind = _build_model(args.model, ghost_bn=args.ghost_bn)
    budget = _parse_bytes(args.hbm_budget)

    mesh = None
    if mesh_axes:
        from incubator_mxnet_tpu.parallel import make_mesh

        mesh = make_mesh(mesh_axes, devices=jax.devices()[:ndev])
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss() if kind == "dense" \
        or args.model == "resnet50" else gluon.loss.L2Loss()
    kw = dict(optimizer=args.optimizer, learning_rate=0.1)
    if args.optimizer == "sgd":
        kw["momentum"] = args.momentum
    if args.multi_precision:
        kw["multi_precision"] = True
    step = make_train_step(
        net, loss_fn, mesh=mesh, zero=args.zero,
        pipeline_stages=args.pipeline_stages, num_micro=args.num_micro,
        pipeline_remat=args.pipeline_remat, donate=not args.no_donate,
        compute_dtype=args.compute_dtype, lint="off", cost="off",
        hbm_budget=budget, cost_device=args.device,
        # resolve_passes accepts the raw comma string; () = explicitly
        # none (an absent flag must not absorb MXTPU_PASSES here — the
        # CLI's output should reflect its own arguments only)
        passes=args.passes if args.passes else (), **kw)

    x = jax.ShapeDtypeStruct((args.batch,) + in_shape, jnp.float32)
    if args.model == "conv-bn":
        y = jax.ShapeDtypeStruct((args.batch, 16, 16, 16), jnp.float32)
    else:
        y = jax.ShapeDtypeStruct((args.batch,), jnp.float32)
    report = step.analyze_cost(x, y, device=args.device, hbm_budget=budget)

    if args.diff:
        worst, _ = _diff_profile(report, args.diff, args.drift_threshold,
                                 args.fmt)
        return 2 if worst > args.drift_threshold else 0

    if args.fmt == "json":
        print(report.to_json(indent=2))
    else:
        print(report.format())
    errors = [d for d in report.diagnostics
              if d.severity >= Severity.ERROR]
    if errors and args.fmt != "json":
        print("graftcost: %d error(s) — infeasible config" % len(errors),
              file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
