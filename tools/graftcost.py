#!/usr/bin/env python
"""graftcost CLI — trace-time cost report for a model + mesh + knob set.

Builds the requested model, constructs the fused train step with the
given parallelism knobs, and costs its traced program WITHOUT compiling
or running a step (``analysis/cost_model.py``; catalog and field
reference in docs/ANALYSIS.md): per-category FLOPs / fusion-aware HBM
bytes, peak live-buffer memory (donation-, remat- and ZeRO-sharding-
aware), per-mesh-axis collective volume, and the roofline step-time
estimate for a registry device (``tpu-v5e`` default, ``cpu-proxy`` for
off-chip relative numbers).

Exit status 1 when any error-severity GL2xx diagnostic fires — with
``--hbm-budget`` this is the eager infeasibility gate (GL201) the
autotuner (ROADMAP item 4) uses to reject configs before paying a
compile.

Usage::

    python tools/graftcost.py --model dense --batch 16
    python tools/graftcost.py --model resnet50 --batch 256 --compute-dtype
        bfloat16 --format json
    python tools/graftcost.py --model dense --mesh dp=8 --zero 1
        --hbm-budget 16GiB
"""
from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _parse_mesh(spec):
    """'dp=8' / 'dp=2,pp=4' -> ordered dict of axis sizes."""
    axes = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        if not size:
            raise SystemExit("--mesh entries are axis=size, got %r" % part)
        axes[name.strip()] = int(size)
    return axes


def _parse_bytes(s):
    """'16GiB' / '8GB' / '1048576' -> bytes."""
    if s is None:
        return None
    s = str(s).strip()
    units = {"kib": 2**10, "mib": 2**20, "gib": 2**30, "tib": 2**40,
             "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12,
             "b": 1}
    low = s.lower()
    for u in sorted(units, key=len, reverse=True):
        if low.endswith(u):
            return float(low[: -len(u)]) * units[u]
    return float(s)


def _build_model(name, feat=16, layers=4):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    if name == "dense":
        # the tests/test_zero_sharding.py net: 4 x Dense(16)
        net = nn.HybridSequential()
        for _ in range(layers):
            net.add(nn.Dense(feat, activation="tanh"))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, feat)))
        return net, (feat,), "dense"
    if name == "conv-bn":
        net = nn.HybridSequential()
        net.add(nn.Conv2D(16, 3, padding=1, in_channels=3))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2D(16, 3, padding=1, in_channels=16))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 3, 16, 16)))
        return net, (3, 16, 16), "conv"
    if name == "resnet50":
        from incubator_mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet50_v1(classes=1000)
        net.initialize(init=mx.init.Zero())
        net.shape_init((1, 3, 224, 224))
        return net, (3, 224, 224), "conv"
    raise SystemExit("unknown --model %r (dense, conv-bn, resnet50)" % name)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcost", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--model", default="dense",
                    choices=["dense", "conv-bn", "resnet50"])
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--mesh", default="",
                    help="mesh axes, e.g. dp=8 or dp=2,pp=4 (devices are "
                         "CPU-forged off-chip)")
    ap.add_argument("--optimizer", default="sgd",
                    choices=["sgd", "adam"])
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--zero", type=int, default=0, choices=[0, 1])
    ap.add_argument("--multi-precision", action="store_true")
    ap.add_argument("--pipeline-stages", type=int, default=None)
    ap.add_argument("--num-micro", type=int, default=1)
    ap.add_argument("--pipeline-remat", action="store_true")
    ap.add_argument("--no-donate", action="store_true")
    ap.add_argument("--compute-dtype", default=None,
                    help="e.g. bfloat16 (default: f32)")
    ap.add_argument("--device", default="tpu-v5e",
                    help="roofline device-spec registry key")
    ap.add_argument("--hbm-budget", default=None,
                    help="peak-memory budget (bytes; 16GiB / 8GB forms "
                         "accepted) — GL201 errors over it, exit 1")
    ap.add_argument("--format", dest="fmt", default="table",
                    choices=["table", "json"])
    args = ap.parse_args(argv)

    mesh_axes = _parse_mesh(args.mesh)
    ndev = 1
    for v in mesh_axes.values():
        ndev *= v
    if mesh_axes and "XLA_FLAGS" not in os.environ:
        # forge enough host devices for the mesh BEFORE jax initializes
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=%d" % max(ndev, 2)

    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.analysis import DEVICE_SPECS, Severity
    from incubator_mxnet_tpu.parallel import make_train_step
    from incubator_mxnet_tpu import gluon

    if args.device not in DEVICE_SPECS:
        raise SystemExit("unknown --device %r (registry: %s)"
                         % (args.device, sorted(DEVICE_SPECS)))
    net, in_shape, kind = _build_model(args.model)
    budget = _parse_bytes(args.hbm_budget)

    mesh = None
    if mesh_axes:
        from incubator_mxnet_tpu.parallel import make_mesh

        mesh = make_mesh(mesh_axes, devices=jax.devices()[:ndev])
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss() if kind == "dense" \
        or args.model == "resnet50" else gluon.loss.L2Loss()
    kw = dict(optimizer=args.optimizer, learning_rate=0.1)
    if args.optimizer == "sgd":
        kw["momentum"] = args.momentum
    if args.multi_precision:
        kw["multi_precision"] = True
    step = make_train_step(
        net, loss_fn, mesh=mesh, zero=args.zero,
        pipeline_stages=args.pipeline_stages, num_micro=args.num_micro,
        pipeline_remat=args.pipeline_remat, donate=not args.no_donate,
        compute_dtype=args.compute_dtype, lint="off", cost="off",
        hbm_budget=budget, cost_device=args.device, **kw)

    x = jax.ShapeDtypeStruct((args.batch,) + in_shape, jnp.float32)
    if args.model == "conv-bn":
        y = jax.ShapeDtypeStruct((args.batch, 16, 16, 16), jnp.float32)
    else:
        y = jax.ShapeDtypeStruct((args.batch,), jnp.float32)
    report = step.analyze_cost(x, y, device=args.device, hbm_budget=budget)

    if args.fmt == "json":
        print(report.to_json(indent=2))
    else:
        print(report.format())
    errors = [d for d in report.diagnostics
              if d.severity >= Severity.ERROR]
    if errors and args.fmt != "json":
        print("graftcost: %d error(s) — infeasible config" % len(errors),
              file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
