#!/usr/bin/env python
"""Parse a training log (the Speedometer/fit epoch lines) into a
markdown table (reference: tools/parse_log.py — same regexes over
``Epoch[N] Train-<metric>=V`` / ``Validation-<metric>=V`` /
``Epoch[N] Time cost=V`` lines, which this framework's
``mx.callback.Speedometer`` + ``module.fit`` logging also emits).

Usage: python tools/parse_log.py train.log [--metric-names accuracy ...]
"""
import argparse
import re


def parse(lines, metric_names):
    # anchor the metric name directly to '=' — a trailing wildcard would
    # let 'accuracy' absorb 'accuracy_top5' lines
    res = ([re.compile(r".*Epoch\[(\d+)\] Train-" + re.escape(s)
                       + r"=([.\d]+)") for s in metric_names]
           + [re.compile(r".*Epoch\[(\d+)\] Validation-" + re.escape(s)
                         + r"=([.\d]+)") for s in metric_names]
           + [re.compile(r".*Epoch\[(\d+)\] Time.*=([.\d]+)")])
    data = {}
    for line in lines:
        for i, r in enumerate(res):
            m = r.match(line)
            if m is None:
                continue
            epoch, val = int(m.group(1)), float(m.group(2))
            cnt_sum = data.setdefault(epoch, [[0, 0.0]
                                              for _ in range(len(res))])
            cnt_sum[i][0] += 1
            cnt_sum[i][1] += val
            break
    return data, len(metric_names)


def main():
    ap = argparse.ArgumentParser(description="Parse a training log")
    ap.add_argument("logfile")
    ap.add_argument("--format", default="markdown",
                    choices=["markdown", "none"])
    ap.add_argument("--metric-names", nargs="+", default=["accuracy"])
    args = ap.parse_args()
    with open(args.logfile) as f:
        data, nm = parse(f.readlines(), args.metric_names)

    heads = (["epoch"] + ["train-" + s for s in args.metric_names]
             + ["val-" + s for s in args.metric_names] + ["time"])
    if args.format == "markdown":
        print("| " + " | ".join(heads) + " |")
        print("|" + " --- |" * len(heads))
    for epoch in sorted(data):
        row = [str(epoch)]
        for cnt, tot in data[epoch]:
            row.append("%.6g" % (tot / cnt) if cnt else "-")
        sep = " | " if args.format == "markdown" else " "
        line = sep.join(row)
        print(("| %s |" % line) if args.format == "markdown" else line)


if __name__ == "__main__":
    main()
