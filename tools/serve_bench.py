#!/usr/bin/env python
"""Open-loop serving benchmark: ServeEngine + ContinuousBatcher under
Poisson traffic (docs/SERVING.md "Loadtest methodology").

Reports, as JSON lines (the bench.py convention), per measured leg:

  {"metric": "serve_qps", "value": ..., "p50_ms": ..., "p99_ms": ...,
   "occupancy": {...}, "recompiles": 0, ...}

Legs: fp32 (always) and, with ``--int8``, the weight-only quantized
tier — the same traffic replayed (same seed, same arrival process) so
the latency delta is the tier, not the noise.  The run FAILS (exit 1)
if any post-warmup recompile happened: steady-state serving must be
compile-free (the GL005 contract the loadtest counter enforces).

``--chaos`` adds the resilience leg (docs/RESILIENCE.md §6): the same
model behind a batcher configured with retry + circuit breaker + int8
fallback tier, driven through the fault_injection serving scenarios —
worker kill (watchdog respawn), engine failure burst (breaker
degradation + recovery), deadline storm (shed-before-compute), and a
canaried hot weight swap incl. a poisoned candidate (rollback) — plus
the flywheel **swap storm** (docs/RESILIENCE.md §9): N back-to-back
canaried promotions (one poisoned) under sustained Poisson load,
measured against a storm-free baseline of the same traffic.  The legs
FAIL (exit 1) on any hung future (a future that did not resolve
within its bound — the no-hang invariant), any post-warmup recompile
(a hot swap must reuse every AOT program), any served row without
exactly-one-version attribution, a storm p99 beyond the declared
bound, or a poisoned swap that did not roll back bitwise.

Examples::

  JAX_PLATFORMS=cpu python tools/serve_bench.py --model mlp --qps 500
  python tools/serve_bench.py --model resnet50 --buckets 32,128 \
      --qps 200 --requests 400 --int8
  python tools/serve_bench.py --model mlp --dp 8 --qps 1000
  JAX_PLATFORMS=cpu python tools/serve_bench.py --model mlp --chaos
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = time.time()


def log(msg):
    print("[serve_bench %6.1fs] %s" % (time.time() - T0, msg),
          file=sys.stderr, flush=True)


def build_model(name, image_size):
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    if name == "mlp":
        net = nn.HybridSequential()
        net.add(nn.Dense(256, activation="relu"),
                nn.Dense(256, activation="relu"), nn.Dense(64))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 128)))
        return net, (128,)
    if name == "cnn":
        net = nn.HybridSequential()
        net.add(nn.Conv2D(16, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.MaxPool2D(2),
                nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.GlobalAvgPool2D(),
                nn.Flatten(), nn.Dense(10))
        net.initialize(init=mx.init.Xavier())
        net(nd.random.uniform(shape=(2, 3, image_size, image_size)))
        return net, (3, image_size, image_size)
    if name == "resnet50":
        from incubator_mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet50_v1(classes=1000)
        net.initialize(init=mx.init.Xavier())
        net(nd.random.uniform(shape=(1, 3, image_size, image_size)))
        return net, (3, image_size, image_size)
    raise SystemExit("unknown --model %r" % name)


def run_leg(tag, net, sample_shape, args, mesh, dtype=None):
    import numpy as np

    from incubator_mxnet_tpu.serve import (ContinuousBatcher, ServeEngine,
                                           poisson_loadtest)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    eng = ServeEngine(net, buckets=buckets, mesh=mesh, dtype=dtype,
                      lint="error", cost=args.cost)
    t = eng.warmup(np.zeros(sample_shape, np.float32))
    log("%s: warmed %d buckets (trace %.2fs, compile %.2fs)"
        % (tag, len(buckets), t["trace"], t["compile"]))
    rs = np.random.RandomState(args.seed)
    pool = rs.rand(64, *sample_shape).astype(np.float32)
    batcher = ContinuousBatcher(eng, max_delay=args.max_delay / 1e3,
                                max_queue=args.max_queue)
    try:
        rep = poisson_loadtest(batcher, lambda i, rng: pool[i % 64],
                               qps=args.qps, n_requests=args.requests,
                               seed=args.seed,
                               extra={"leg": tag, "model": args.model,
                                      "buckets": list(buckets),
                                      "warmup_compile_s":
                                          round(t["compile"], 2)})
    finally:
        batcher.close()
    log(rep.format())
    rec = {"metric": "serve_qps", "value": round(rep.qps_sustained, 2),
           "unit": "req/s", "leg": tag, "model": args.model,
           "qps_offered": args.qps,
           "p50_ms": round(rep.p50_ms, 3), "p95_ms": round(rep.p95_ms, 3),
           "p99_ms": round(rep.p99_ms, 3),
           "ok": rep.ok, "errors": rep.errors, "shed": rep.shed,
           "occupancy": {str(k): v for k, v in
                         sorted(rep.occupancy.items())},
           "flush_full": rep.flush_full,
           "flush_deadline": rep.flush_deadline,
           "recompiles": rep.recompiles,
           "buckets": list(buckets), "max_delay_ms": args.max_delay}
    print(json.dumps(rec), flush=True)
    return rep


def run_chaos(net, sample_shape, args, mesh):
    """The resilience leg: chaos scenarios against a breaker+fallback
    batcher.  Returns the number of FAILURES (hung futures + post-
    warmup recompiles) — 0 is the contract."""
    import numpy as np

    from incubator_mxnet_tpu.parallel import fault_injection as fi
    from incubator_mxnet_tpu.serve import (CircuitBreaker,
                                           ContinuousBatcher, RetryPolicy,
                                           ServeEngine, SwapRejected)
    from incubator_mxnet_tpu.serve.resilience import classify_future

    buckets = tuple(int(b) for b in args.buckets.split(","))
    eng = ServeEngine(net, buckets=buckets, mesh=mesh,
                      lint="error", cost=args.cost)
    eng.warmup(np.zeros(sample_shape, np.float32))
    fb = ServeEngine(net, buckets=buckets, mesh=mesh, dtype="int8",
                     lint="error")
    fb.warmup(np.zeros(sample_shape, np.float32))
    recompiles0 = eng.recompile_count + fb.recompile_count
    rs = np.random.RandomState(args.seed)
    pool = rs.rand(64, *sample_shape).astype(np.float32)
    batcher = ContinuousBatcher(
        eng, max_delay=args.max_delay / 1e3, max_queue=args.max_queue,
        retry=RetryPolicy(max_retries=1, backoff=0.002),
        breaker=CircuitBreaker(failure_threshold=3, recovery_time=0.1),
        fallback=fb, grace=0.05)
    hung = served = expired = shed = degraded = failed = 0
    poison_accepted = False

    def drain(futures, bound=15.0):
        nonlocal hung, served, expired, shed, degraded, failed
        import time as _time

        end = _time.monotonic() + bound  # wall-clock steps must not
        for f in futures:                # corrupt the no-hang bound
            outcome = classify_future(f, end - _time.monotonic())
            if outcome == "ok":
                served += 1
                if getattr(f, "_mxtpu_tier", None) == "fallback":
                    degraded += 1
            elif outcome == "expired":
                expired += 1
            elif outcome == "shed":
                shed += 1
            elif outcome == "hung":
                hung += 1  # the no-hang invariant breach
            else:
                failed += 1

    try:
        # 1. worker kill mid-traffic: watchdog fails the lost batch,
        # respawns, later traffic serves again
        with fi.kill_batcher_worker(at=0):
            drain([batcher.submit(pool[i % 64]) for i in range(8)])
        log("chaos: worker kill — respawns=%d worker_deaths=%d"
            % (batcher.stats.respawns, batcher.stats.worker_deaths))
        # 2. engine failure burst on the PRIMARY only: retry absorbs the
        # head, the breaker opens and degrades to the int8 tier, then
        # half-opens and recovers
        with fi.engine_failure_burst(8, engine=eng):
            drain([batcher.submit(pool[i % 64]) for i in range(12)])
        time.sleep(0.15)  # past recovery_time: next batch probes
        drain([batcher.submit(pool[0])])
        log("chaos: failure burst — breaker=%s degraded=%d retried=%d"
            % (batcher.breaker.state, batcher.stats.degraded,
               batcher.stats.retried))
        # 3. deadline storm: already-dead work shed BEFORE compute
        futs, _ = fi.deadline_storm(batcher, [pool[0]] * 16,
                                    deadline=1e-4)
        drain(futs)
        log("chaos: deadline storm — expired=%d" % batcher.stats.expired)
        # 4. canaried hot swap under the same engine: a legitimate
        # candidate commits with zero recompiles; a poisoned one rolls
        # back (SwapRejected) with the old version still serving
        new = [np.array(p._data._data) for p in eng._params]
        v = eng.update_params(new)
        try:
            eng.update_params(fi.nan_params(eng))
            log("chaos: FAIL — poisoned swap was accepted")
            poison_accepted = True
        except SwapRejected:
            pass
        drain([batcher.submit(pool[i % 64]) for i in range(4)])
        log("chaos: hot swap — version=%d rollbacks=%d"
            % (v, eng.rollback_count))
    finally:
        batcher.close()
    recompiles = (eng.recompile_count + fb.recompile_count) - recompiles0
    rec = {"metric": "serve_chaos", "value": hung, "unit": "hung_futures",
           "served": served, "failed": failed, "expired": expired,
           "breaker_shed": shed, "degraded": degraded,
           "retried": batcher.stats.retried,
           "respawns": batcher.stats.respawns,
           "worker_deaths": batcher.stats.worker_deaths,
           "breaker_state": batcher.breaker.state,
           "swap_version": eng.params_version,
           "rollbacks": eng.rollback_count,
           "poison_accepted": poison_accepted,
           "recompiles": recompiles}
    print(json.dumps(rec), flush=True)
    if hung or recompiles or poison_accepted:
        log("chaos: FAIL — %d hung future(s), %d recompile(s), "
            "poison_accepted=%s" % (hung, recompiles, poison_accepted))
        return 1
    log("chaos: ok — every future resolved, 0 recompiles")
    return 0


def run_swap_storm(net, sample_shape, args, mesh):
    """The flywheel chaos leg (docs/RESILIENCE.md §9): N back-to-back
    canaried hot swaps — including one poisoned candidate — under
    sustained Poisson load, measured against a storm-free baseline of
    the SAME traffic (same seed, same arrival process).  Returns the
    number of failures: post-warmup recompiles, hung futures,
    unattributed versions, a p99 beyond the declared bound, or a
    poison swap that was accepted / did not restore the incumbent
    bitwise."""
    import numpy as np

    from incubator_mxnet_tpu.parallel import fault_injection as fi
    from incubator_mxnet_tpu.serve import (ContinuousBatcher, ServeEngine,
                                           poisson_loadtest)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    eng = ServeEngine(net, buckets=buckets, mesh=mesh,
                      lint="error", cost=args.cost)
    eng.warmup(np.zeros(sample_shape, np.float32))
    recompiles0 = eng.recompile_count
    rs = np.random.RandomState(args.seed)
    pool = rs.rand(64, *sample_shape).astype(np.float32)
    batcher = ContinuousBatcher(eng, max_delay=args.max_delay / 1e3,
                                max_queue=args.max_queue)
    try:
        base = poisson_loadtest(batcher, lambda i, rng: pool[i % 64],
                                qps=args.qps, n_requests=args.requests,
                                seed=args.seed,
                                extra={"leg": "storm_baseline"})
        log("storm baseline: " + base.format())
        with fi.swap_storm(eng, n_swaps=args.storm_swaps,
                           interval=0.02, poison_at=args.storm_swaps // 2,
                           seed=args.seed) as st:
            storm = poisson_loadtest(batcher, lambda i, rng: pool[i % 64],
                                     qps=args.qps,
                                     n_requests=args.requests,
                                     seed=args.seed,
                                     extra={"leg": "swap_storm"})
        log("swap storm:     " + storm.format())
    finally:
        batcher.close()
    # declared p99 bound: generous against the host's ~3x speed
    # variance — the claim is "a swap storm does not blow up the tail",
    # not a microbenchmark
    bound_ms = base.p99_ms * 10.0 + 250.0
    recompiles = eng.recompile_count - recompiles0
    failures = 0
    if recompiles:
        log("swap storm: FAIL — %d post-warmup recompile(s); a swap is "
            "zero-recompile by GL011 construction" % recompiles)
        failures += 1
    if base.hung or storm.hung:
        log("swap storm: FAIL — hung futures (baseline %d, storm %d)"
            % (base.hung, storm.hung))
        failures += 1
    if storm.unattributed or base.unattributed:
        log("swap storm: FAIL — %d row(s) without exactly-one-version "
            "attribution" % (storm.unattributed + base.unattributed))
        failures += 1
    if storm.p99_ms > bound_ms:
        log("swap storm: FAIL — p99 %.2fms beyond the declared bound "
            "%.2fms (baseline %.2fms)"
            % (storm.p99_ms, bound_ms, base.p99_ms))
        failures += 1
    if st.error or not st.poison_rejected or not st.incumbent_bitwise_ok:
        log("swap storm: FAIL — storm error=%r poison_rejected=%s "
            "incumbent_bitwise_ok=%s"
            % (st.error, st.poison_rejected, st.incumbent_bitwise_ok))
        failures += 1
    if not st.committed:
        log("swap storm: FAIL — 0 swaps landed, nothing stress-tested")
        failures += 1
    rec = {"metric": "serve_swap_storm",
           "value": round(storm.p99_ms - base.p99_ms, 3), "unit": "ms",
           "baseline_p99_ms": round(base.p99_ms, 3),
           "storm_p99_ms": round(storm.p99_ms, 3),
           "bound_ms": round(bound_ms, 3),
           "swaps_attempted": st.attempted, "swaps_committed": st.committed,
           "promotions": storm.promotions, "rollbacks": storm.rollbacks,
           "versions": storm.versions, "unattributed": storm.unattributed,
           "hung": base.hung + storm.hung, "recompiles": recompiles,
           "poison_rejected": bool(st.poison_rejected),
           "incumbent_bitwise_ok": bool(st.incumbent_bitwise_ok),
           "storm_error": st.error}
    print(json.dumps(rec), flush=True)
    if not failures:
        log("swap storm: ok — %d promotions under load, p99 delta "
            "%.2fms within bound, 0 recompiles, incumbent restored "
            "bitwise on poison"
            % (st.committed, storm.p99_ms - base.p99_ms))
    return failures


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default="mlp",
                    choices=["mlp", "cnn", "resnet50"])
    ap.add_argument("--buckets", default="8,32",
                    help="comma-separated batch buckets (default 8,32)")
    ap.add_argument("--qps", type=float, default=500.0,
                    help="offered open-loop rate (Poisson)")
    ap.add_argument("--requests", type=int, default=300)
    ap.add_argument("--max-delay", type=float, default=5.0,
                    help="batcher deadline, milliseconds")
    ap.add_argument("--max-queue", type=int, default=1024)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--dp", type=int, default=0,
                    help="serve dp-replicated over this many devices")
    ap.add_argument("--int8", action="store_true",
                    help="add the weight-only int8 leg (same traffic)")
    ap.add_argument("--chaos", action="store_true",
                    help="add the resilience legs (worker kill, failure "
                         "burst, deadline storm, hot swap, swap storm "
                         "under load); exit 1 on any hung future, "
                         "recompile, or unattributed version")
    ap.add_argument("--storm-swaps", type=int, default=6,
                    help="swap_storm leg: promotions fired under load "
                         "(one of them poisoned; default 6)")
    ap.add_argument("--cost", default="report",
                    choices=["off", "report", "check"])
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax

    log("devices: %s" % (jax.devices(),))
    mesh = None
    if args.dp:
        from incubator_mxnet_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": args.dp},
                         devices=jax.devices()[:args.dp])
        log("serving dp-replicated over %s" % (mesh,))
    net, sample_shape = build_model(args.model, args.image_size)
    rep = run_leg("fp32", net, sample_shape, args, mesh)
    bad = rep.recompiles
    if args.int8:
        rep8 = run_leg("int8", net, sample_shape, args, mesh, dtype="int8")
        bad += rep8.recompiles
        delta = rep8.p99_ms - rep.p99_ms
        print(json.dumps({"metric": "serve_int8_p99_delta_ms",
                          "value": round(delta, 3), "unit": "ms",
                          "fp32_p99_ms": round(rep.p99_ms, 3),
                          "int8_p99_ms": round(rep8.p99_ms, 3)}),
              flush=True)
    if args.chaos:
        bad += run_chaos(net, sample_shape, args, mesh)
        bad += run_swap_storm(net, sample_shape, args, mesh)
    if bad:
        log("FAIL: %d post-warmup recompile(s) / chaos failure(s) — "
            "steady-state serving must be compile-free and hang-free"
            % bad)
        sys.exit(1)


if __name__ == "__main__":
    main()
