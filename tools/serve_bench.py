#!/usr/bin/env python
"""Open-loop serving benchmark: ServeEngine + ContinuousBatcher under
Poisson traffic (docs/SERVING.md "Loadtest methodology").

Reports, as JSON lines (the bench.py convention), per measured leg:

  {"metric": "serve_qps", "value": ..., "p50_ms": ..., "p99_ms": ...,
   "occupancy": {...}, "recompiles": 0, ...}

Legs: fp32 (always) and, with ``--int8``, the weight-only quantized
tier — the same traffic replayed (same seed, same arrival process) so
the latency delta is the tier, not the noise.  The run FAILS (exit 1)
if any post-warmup recompile happened: steady-state serving must be
compile-free (the GL005 contract the loadtest counter enforces).

Examples::

  JAX_PLATFORMS=cpu python tools/serve_bench.py --model mlp --qps 500
  python tools/serve_bench.py --model resnet50 --buckets 32,128 \
      --qps 200 --requests 400 --int8
  python tools/serve_bench.py --model mlp --dp 8 --qps 1000
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = time.time()


def log(msg):
    print("[serve_bench %6.1fs] %s" % (time.time() - T0, msg),
          file=sys.stderr, flush=True)


def build_model(name, image_size):
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    if name == "mlp":
        net = nn.HybridSequential()
        net.add(nn.Dense(256, activation="relu"),
                nn.Dense(256, activation="relu"), nn.Dense(64))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 128)))
        return net, (128,)
    if name == "cnn":
        net = nn.HybridSequential()
        net.add(nn.Conv2D(16, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.MaxPool2D(2),
                nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.GlobalAvgPool2D(),
                nn.Flatten(), nn.Dense(10))
        net.initialize(init=mx.init.Xavier())
        net(nd.random.uniform(shape=(2, 3, image_size, image_size)))
        return net, (3, image_size, image_size)
    if name == "resnet50":
        from incubator_mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet50_v1(classes=1000)
        net.initialize(init=mx.init.Xavier())
        net(nd.random.uniform(shape=(1, 3, image_size, image_size)))
        return net, (3, image_size, image_size)
    raise SystemExit("unknown --model %r" % name)


def run_leg(tag, net, sample_shape, args, mesh, dtype=None):
    import numpy as np

    from incubator_mxnet_tpu.serve import (ContinuousBatcher, ServeEngine,
                                           poisson_loadtest)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    eng = ServeEngine(net, buckets=buckets, mesh=mesh, dtype=dtype,
                      lint="error", cost=args.cost)
    t = eng.warmup(np.zeros(sample_shape, np.float32))
    log("%s: warmed %d buckets (trace %.2fs, compile %.2fs)"
        % (tag, len(buckets), t["trace"], t["compile"]))
    rs = np.random.RandomState(args.seed)
    pool = rs.rand(64, *sample_shape).astype(np.float32)
    batcher = ContinuousBatcher(eng, max_delay=args.max_delay / 1e3,
                                max_queue=args.max_queue)
    try:
        rep = poisson_loadtest(batcher, lambda i, rng: pool[i % 64],
                               qps=args.qps, n_requests=args.requests,
                               seed=args.seed,
                               extra={"leg": tag, "model": args.model,
                                      "buckets": list(buckets),
                                      "warmup_compile_s":
                                          round(t["compile"], 2)})
    finally:
        batcher.close()
    log(rep.format())
    rec = {"metric": "serve_qps", "value": round(rep.qps_sustained, 2),
           "unit": "req/s", "leg": tag, "model": args.model,
           "qps_offered": args.qps,
           "p50_ms": round(rep.p50_ms, 3), "p95_ms": round(rep.p95_ms, 3),
           "p99_ms": round(rep.p99_ms, 3),
           "ok": rep.ok, "errors": rep.errors, "shed": rep.shed,
           "occupancy": {str(k): v for k, v in
                         sorted(rep.occupancy.items())},
           "flush_full": rep.flush_full,
           "flush_deadline": rep.flush_deadline,
           "recompiles": rep.recompiles,
           "buckets": list(buckets), "max_delay_ms": args.max_delay}
    print(json.dumps(rec), flush=True)
    return rep


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default="mlp",
                    choices=["mlp", "cnn", "resnet50"])
    ap.add_argument("--buckets", default="8,32",
                    help="comma-separated batch buckets (default 8,32)")
    ap.add_argument("--qps", type=float, default=500.0,
                    help="offered open-loop rate (Poisson)")
    ap.add_argument("--requests", type=int, default=300)
    ap.add_argument("--max-delay", type=float, default=5.0,
                    help="batcher deadline, milliseconds")
    ap.add_argument("--max-queue", type=int, default=1024)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--dp", type=int, default=0,
                    help="serve dp-replicated over this many devices")
    ap.add_argument("--int8", action="store_true",
                    help="add the weight-only int8 leg (same traffic)")
    ap.add_argument("--cost", default="report",
                    choices=["off", "report", "check"])
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax

    log("devices: %s" % (jax.devices(),))
    mesh = None
    if args.dp:
        from incubator_mxnet_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": args.dp},
                         devices=jax.devices()[:args.dp])
        log("serving dp-replicated over %s" % (mesh,))
    net, sample_shape = build_model(args.model, args.image_size)
    rep = run_leg("fp32", net, sample_shape, args, mesh)
    bad = rep.recompiles
    if args.int8:
        rep8 = run_leg("int8", net, sample_shape, args, mesh, dtype="int8")
        bad += rep8.recompiles
        delta = rep8.p99_ms - rep.p99_ms
        print(json.dumps({"metric": "serve_int8_p99_delta_ms",
                          "value": round(delta, 3), "unit": "ms",
                          "fp32_p99_ms": round(rep.p99_ms, 3),
                          "int8_p99_ms": round(rep8.p99_ms, 3)}),
              flush=True)
    if bad:
        log("FAIL: %d post-warmup recompile(s) — steady-state serving "
            "must be compile-free" % bad)
        sys.exit(1)


if __name__ == "__main__":
    main()
