#!/usr/bin/env python
"""im2rec: build .lst / .rec(.idx) record files from an image directory.

Capability parity with the reference's ``tools/im2rec.py`` / ``im2rec.cc``:
  * ``--list`` mode walks an image root, assigns integer labels per
    subdirectory, and writes ``prefix.lst`` (TSV: index, label..., relpath);
  * record mode reads a ``.lst`` and packs (optionally re-encoded/resized)
    images into ``prefix.rec`` + ``prefix.idx`` readable by
    ``mx.io.ImageRecordIter`` and by stock dmlc-recordio readers
    (byte-compatible wire format, see ``incubator_mxnet_tpu/recordio.py``).

Multiprocess packing: a worker pool encodes images; the writer thread
appends in index order.
"""
import argparse
import os
import random
import sys
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".npy")


def make_list(args):
    root = args.root
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    items = []
    if classes:
        for c in classes:
            for dirpath, _dirs, files in os.walk(os.path.join(root, c)):
                for f in sorted(files):
                    if f.lower().endswith(EXTS):
                        rel = os.path.relpath(os.path.join(dirpath, f), root)
                        items.append((rel, label_of[c]))
    else:
        for dirpath, _dirs, files in os.walk(root):
            for f in sorted(files):
                if f.lower().endswith(EXTS):
                    rel = os.path.relpath(os.path.join(dirpath, f), root)
                    items.append((rel, 0))
    if args.shuffle:
        random.Random(args.seed).shuffle(items)
    n_test = int(len(items) * args.test_ratio)
    n_train = int(len(items) * args.train_ratio)
    chunks = {"": items}
    if args.test_ratio > 0 or args.train_ratio < 1:
        chunks = {"_train": items[:n_train],
                  "_test": items[n_train:n_train + n_test]}
        if n_train + n_test < len(items):
            chunks["_val"] = items[n_train + n_test:]
    for suffix, chunk in chunks.items():
        path = args.prefix + suffix + ".lst"
        with open(path, "w") as f:
            for i, (rel, lab) in enumerate(chunk):
                f.write("%d\t%f\t%s\n" % (i, float(lab), rel))
        print("wrote %s (%d items)" % (path, len(chunk)))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def _encode_one(args, root, rel):
    import numpy as np

    from incubator_mxnet_tpu.recordio import _imencode

    path = os.path.join(root, rel)
    if path.lower().endswith(".npy"):
        img = np.load(path)
    else:
        from PIL import Image

        img = np.asarray(Image.open(path).convert("RGB"))
    if args.resize > 0:
        from PIL import Image

        h, w = img.shape[:2]
        s = args.resize / min(h, w)
        img = np.asarray(Image.fromarray(img.astype(np.uint8)).resize(
            (max(int(round(w * s)), args.resize),
             max(int(round(h * s)), args.resize)), Image.BILINEAR))
    fmt = ".npy" if args.pack_npy else (args.encoding or ".jpg")
    return _imencode(img, quality=args.quality, img_fmt=fmt)


def make_record(args):
    from incubator_mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, pack

    lst = args.prefix + ".lst" if os.path.isdir(args.root) and \
        not args.lst else (args.lst or args.prefix + ".lst")
    rec = MXIndexedRecordIO(args.prefix + ".idx", args.prefix + ".rec", "w")
    items = list(read_list(lst))
    pool = ThreadPoolExecutor(max_workers=args.num_thread)
    bufs = pool.map(lambda it: _encode_one(args, args.root, it[2]), items)
    n = 0
    for (idx, labels, _rel), buf in zip(items, bufs):
        label = labels[0] if len(labels) == 1 else labels
        header = IRHeader(0, label, idx, 0)
        rec.write_idx(idx, pack(header, buf))
        n += 1
        if n % 1000 == 0:
            print("packed %d" % n)
    rec.close()
    print("wrote %s.rec / %s.idx (%d records)" % (args.prefix, args.prefix, n))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (prefix.lst/rec/idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="make .lst instead of .rec")
    ap.add_argument("--lst", default=None, help="existing .lst to pack")
    ap.add_argument("--resize", type=int, default=-1)
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--encoding", default=".jpg")
    ap.add_argument("--pack-npy", action="store_true",
                    help="store raw npy payloads (no PIL needed to read)")
    ap.add_argument("--num-thread", type=int, default=4)
    ap.add_argument("--shuffle", type=int, default=1)
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--test-ratio", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.list:
        make_list(args)
    else:
        make_record(args)


if __name__ == "__main__":
    main()
