"""Reflection-driven operator case synthesis.

For every distinct op in the registry, synthesize a concrete call
(input arrays + attrs) that the op accepts, using its ``op_info``
signature plus a curated hint table for shape-constrained families
(conv/pool/rnn/indexing/...).  Consumers:

* ``tests/test_op_sweep.py`` — CPU forward sweep vs ``op.infer``
  metadata + numeric-gradient checks on differentiable ops (the
  reference's ``check_numeric_gradient``-everywhere strategy,
  tests/python/unittest/test_operator.py).
* ``tools/check_consistency.py`` — TPU-vs-CPU forward battery over the
  same cases (the reference's cross-device consistency harness,
  python/mxnet/test_utils.py:1422).

``build_cases()`` returns ``{op_name: (arrays, attrs) or None}`` —
None means no generic candidate fit and no hint exists (reported, so
coverage is measurable, never silently truncated).
"""
from __future__ import annotations

import sys

import numpy as np

_RNG = np.random.RandomState(0)


def _f(*shape):
    return (_RNG.uniform(0.3, 1.7, shape)).astype(np.float32)


def _fn(*shape):
    return _RNG.normal(0.0, 1.0, shape).astype(np.float32)


def _idx(hi, *shape):
    # int32: index-like inputs must not be float, or the numeric-gradient
    # sweep would perturb them across integer boundaries
    return _RNG.randint(0, hi, shape).astype(np.int32)


# --------------------------------------------------------------------------
# curated hints: op -> (arrays, attrs); lazily evaluated so np draws are
# deterministic per build_cases() call
# --------------------------------------------------------------------------

def _hints():
    B, C, H, W = 2, 4, 8, 8
    x4 = _fn(B, C, H, W)
    T, N, I, S = 5, 2, 3, 4  # rnn: time, batch, input, state
    h = {
        # --- nn core ---
        "Convolution": ([_fn(B, C, H, W), _fn(8, C, 3, 3), _fn(8)],
                        {"kernel": (3, 3), "num_filter": 8, "pad": (1, 1)}),
        "Deconvolution": ([_fn(B, C, H, W), _fn(C, 8, 3, 3), _fn(8)],
                          {"kernel": (3, 3), "num_filter": 8}),
        "Pooling": ([x4], {"kernel": (2, 2), "stride": (2, 2),
                           "pool_type": "max"}),
        "Pooling_v1": ([x4], {"kernel": (2, 2), "stride": (2, 2),
                              "pool_type": "avg"}),
        "FullyConnected": ([_fn(B, 6), _fn(5, 6), _fn(5)],
                           {"num_hidden": 5}),
        "BatchNorm": ([x4, _f(C), _fn(C), _fn(C), _f(C)], {}),
        "BatchNorm_v1": ([x4, _f(C), _fn(C), _fn(C), _f(C)], {}),
        "_contrib_SyncBatchNorm": ([x4, _f(C), _fn(C), _fn(C), _f(C)],
                                   {"key": "sweep"}),
        # stats-free fused ghost-BN (the pipeline-parallel form): no
        # moving-stat inputs, ghost group over the batch
        "_contrib_GhostBNReLUNS": ([x4, _f(C), _fn(C)], {"group": 2}),
        "_contrib_GhostBNNS": ([x4, _f(C), _fn(C)], {"group": 2}),
        "LayerNorm": ([_fn(B, 6), _f(6), _fn(6)], {}),
        "GroupNorm": ([x4, _f(C), _fn(C)], {"num_groups": 2}),
        "InstanceNorm": ([x4, _f(C), _fn(C)], {}),
        "L2Normalization": ([x4], {}),
        "LRN": ([x4], {"nsize": 3}),
        "SoftmaxActivation": ([_fn(B, 6)], {}),
        "SoftmaxOutput": ([_fn(B, 6), _idx(6, B)], {}),
        "Softmax": ([_fn(B, 6), _idx(6, B)], {}),
        "softmax": ([_fn(B, 6)], {}),
        "log_softmax": ([_fn(B, 6)], {}),
        "softmin": ([_fn(B, 6)], {}),
        "masked_softmax": ([_fn(B, 6),
                            (_RNG.rand(B, 6) > 0.3)], {}),
        "masked_log_softmax": ([_fn(B, 6),
                                (_RNG.rand(B, 6) > 0.3)], {}),
        "Activation": ([x4], {"act_type": "relu"}),
        "LeakyReLU": ([x4], {}),
        "PReLU": ([x4, _f(1)], {"act_type": "prelu"}),
        "Dropout": ([x4], {"key": "sweep"}),
        "CTCLoss": ([_fn(T, B, 6), _idx(5, B, 3) + 1], {}),
        "Correlation": ([x4, _fn(B, C, H, W)], {"kernel_size": 1,
                                                "max_displacement": 2,
                                                "stride1": 1, "stride2": 1}),
        "SpatialTransformer": (
            [x4, _fn(B, 6)],
            {"target_shape": (8, 8), "transform_type": "affine",
             "sampler_type": "bilinear"}),
        "GridGenerator": ([_fn(B, 6)], {"transform_type": "affine",
                                        "target_shape": (8, 8)}),
        "BilinearSampler": ([x4, _RNG.uniform(-1, 1, (B, 2, H, W))
                             .astype(np.float32)], {}),
        "ROIPooling": ([x4, np.array([[0, 0, 0, 4, 4]], np.float32)],
                       {"pooled_size": (2, 2), "spatial_scale": 1.0}),
        "_contrib_ROIAlign": ([x4, np.array([[0, 0, 0, 4, 4]], np.float32)],
                              {"pooled_size": (2, 2), "spatial_scale": 1.0}),
        "UpSampling": ([x4], {"scale": 2, "sample_type": "nearest"}),
        "Pad": ([x4], {"mode": "constant",
                       "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
        "pad": ([x4], {"mode": "constant",
                       "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
        "Embedding": ([_idx(10, B, 3), _fn(10, 5)],
                      {"input_dim": 10, "output_dim": 5}),
        "take": ([_fn(6, 4), _idx(6, B, 2)], {}),
        "batch_take": ([_fn(B, 4), _idx(4, B)], {}),
        "gather_nd": ([_fn(4, 5), _idx(4, 2, 3)], {}),
        "scatter_nd": ([_fn(2, 3), _idx(4, 1, 2)], {"shape": (4, 3)}),
        "_backward_gather_nd": ([_fn(2, 3), _idx(4, 1, 2)],
                                {"shape": (4, 3)}),
        "_scatter_set_nd": ([_fn(4, 3), _fn(2, 3), _idx(4, 1, 2)],
                           {"shape": (4, 3)}),
        "one_hot": ([_idx(5, B, 3)], {"depth": 5}),
        "pick": ([_fn(B, 5), _idx(5, B)], {}),
        "where": ([(_RNG.rand(3, 4) > 0.5), _fn(3, 4), _fn(3, 4)], {}),
        "SequenceMask": ([_fn(T, B, 3), _f(B) + 1], {
            "use_sequence_length": True}),
        "SequenceLast": ([_fn(T, B, 3), _f(B) + 1], {
            "use_sequence_length": True}),
        "SequenceReverse": ([_fn(T, B, 3), _f(B) + 1], {
            "use_sequence_length": True}),
        "RNN": ([_fn(T, N, I), _fn((I + S + 2) * S), _fn(1, N, S)],
                {"state_size": S, "num_layers": 1, "mode": "rnn_tanh",
                 "key": "sweep"}),
        "SliceChannel": ([_fn(B, 4, 3)], {"num_outputs": 2, "axis": 1}),
        "split_v2": ([_fn(B, 4, 3)], {"indices": (2,), "axis": 1}),
        "Concat": ([_fn(B, 3), _fn(B, 3)], {"dim": 1, "num_args": 2}),
        "stack": ([_fn(B, 3), _fn(B, 3)], {"num_args": 2}),
        "add_n": ([_fn(B, 3), _fn(B, 3)], {}),
        "Custom": None,        # needs a registered python CustomOp
        "_CustomFunction": None,
        # --- losses / misc ---
        "MakeLoss": ([_f(B, 3)], {}),
        "smooth_l1": ([_fn(B, 3)], {}),
        "LinearRegressionOutput": ([_fn(B, 3), _fn(B, 3)], {}),
        "MAERegressionOutput": ([_fn(B, 3), _fn(B, 3)], {}),
        "LogisticRegressionOutput": ([_fn(B, 3), _f(B, 3)], {}),
        "SVMOutput": ([_fn(B, 5), _idx(5, B)], {}),
        "IdentityAttachKLSparseReg": ([_f(B, 3)], {}),
        "BlockGrad": ([_fn(B, 3)], {}),
        "CrossDeviceCopy": ([_fn(B, 3)], {}),
        "_identity_with_attr_like_rhs": ([_fn(B, 3), _fn(B, 3)], {}),
        "softmax_cross_entropy": ([_fn(B, 5), _idx(5, B)], {}),
        # --- tensor manipulation needing attrs ---
        "Reshape": ([_fn(B, 6)], {"shape": (3, 4)}),
        "reshape_like": ([_fn(2, 6), _fn(3, 4)], {}),
        "transpose": ([_fn(2, 3, 4)], {}),
        "expand_dims": ([_fn(2, 3)], {"axis": 1}),
        "slice": ([_fn(4, 5)], {"begin": (1, 0), "end": (3, 4)}),
        "slice_axis": ([_fn(4, 5)], {"axis": 0, "begin": 1, "end": 3}),
        "slice_like": ([_fn(4, 5), _fn(2, 3)], {}),
        "_slice_assign": ([_fn(4, 5), _fn(2, 5)],
                          {"begin": (1,), "end": (3,)}),
        "_slice_assign_scalar": ([_fn(4, 5)],
                                 {"begin": (1,), "end": (3,),
                                  "scalar": 1.5}),
        "clip": ([_fn(3, 4)], {"a_min": -0.5, "a_max": 0.5}),
        "repeat": ([_fn(2, 3)], {"repeats": 2}),
        "tile": ([_fn(2, 3)], {"reps": (2, 1)}),
        "reverse": ([_fn(3, 4)], {"axis": 0}),
        "flip": ([_fn(3, 4)], {"axis": 0}),
        "roll": ([_fn(3, 4)], {"shift": 1}),
        "rot90": ([_fn(3, 4)], {}),
        "depth_to_space": ([_fn(B, 8, 2, 2)], {"block_size": 2}),
        "space_to_depth": ([_fn(B, 2, 4, 4)], {"block_size": 2}),
        "swapaxes": ([_fn(2, 3, 4)], {"dim1": 0, "dim2": 2}),
        "Flatten": ([_fn(2, 3, 4)], {}),
        "Cast": ([_fn(2, 3)], {"dtype": "float64"}),
        "amp_cast": ([_fn(2, 3)], {"dtype": "float32"}),
        "amp_multicast": ([_fn(2, 3), _fn(2, 3)], {"num_outputs": 2}),
        "Crop": ([_fn(B, C, 8, 8)], {"h_w": (4, 4), "num_args": 1}),
        "crop": ([_fn(B, C, 8, 8)], {"h_w": (4, 4), "num_args": 1}),
        "pad_v2": None,
        "squeeze": ([_fn(2, 1, 3)], {}),
        "broadcast_to": ([_fn(1, 3)], {"shape": (4, 3)}),
        "broadcast_like": ([_fn(1, 3), _fn(4, 3)], {}),
        "broadcast_axis": ([_fn(1, 3)], {"axis": 0, "size": 4}),
        "cast_storage": ([_fn(3, 4)], {"stype": "default"}),
        # indexing / sorting
        "argsort": ([_fn(3, 4)], {}),
        "topk": ([_fn(3, 6)], {"k": 2}),
        "sort": ([_fn(3, 4)], {}),
        "argmax": ([_fn(3, 4)], {}),
        "argmin": ([_fn(3, 4)], {}),
        "argmax_channel": ([_fn(3, 4)], {}),
        "Dot": ([_fn(3, 4), _fn(4, 5)], {}),
        "dot": ([_fn(3, 4), _fn(4, 5)], {}),
        "batch_dot": ([_fn(B, 3, 4), _fn(B, 4, 5)], {}),
        "diag": ([_fn(4, 4)], {}),
        "norm": ([_fn(3, 4)], {}),
        "IdentityWithLoss": None,
        # --- init-like ops (shape attrs) ---
        "_zeros": ([], {"shape": (2, 3)}),
        "_ones": ([], {"shape": (2, 3)}),
        "_full": ([], {"shape": (2, 3), "value": 1.5}),
        "_eye": ([], {"N": 3}),
        "_arange": ([], {"start": 0, "stop": 6}),
        "_linspace": ([], {"start": 0, "stop": 1, "num": 5}),
        "_zeros_without_dtype": ([], {"shape": (2, 3)}),
        "zeros_like": ([_fn(2, 3)], {}),
        "ones_like": ([_fn(2, 3)], {}),
        "shape_array": ([_fn(2, 3)], {}),
        "size_array": ([_fn(2, 3)], {}),
        # --- long-tail hints (ops the generic candidates can't satisfy) ---
        "_contrib_BilinearResize2D": ([x4], {"height": 4, "width": 4}),
        "_contrib_DeformableConvolution": (
            [_fn(B, C, H, W), _fn(2 * 3 * 3, H, W) * 0 + _fn(B, 2 * 9, H, W),
             _fn(8, C, 3, 3), _fn(8)][0:1]
            + [_fn(B, 2 * 9, H, W), _fn(8, C, 3, 3), _fn(8)],
            {"kernel": (3, 3), "num_filter": 8, "pad": (1, 1)}),
        "_contrib_DeformablePSROIPooling": (
            [_fn(B, 8, H, W), np.array([[0, 0, 0, 4, 4]], np.float32),
             _fn(1, 2 * 2 * 2, 2, 2)],
            {"spatial_scale": 1.0, "output_dim": 2, "group_size": 2,
             "pooled_size": 2, "part_size": 2, "sample_per_part": 2,
             "trans_std": 0.1}),
        "_contrib_MultiBoxDetection": (
            [_f(1, 8, 2), _fn(1, 8 * 4), _RNG.uniform(0.1, 0.4, (1, 8, 4))
             .astype(np.float32)], {}),
        "_contrib_MultiBoxTarget": (
            [_RNG.uniform(0.1, 0.4, (1, 8, 4)).astype(np.float32),
             np.array([[[0, 0.1, 0.1, 0.4, 0.4]]], np.float32),
             _fn(1, 2, 8)], {}),
        "_contrib_Proposal": (
            [_f(1, 2 * 3, 4, 4), _fn(1, 4 * 3, 4, 4),
             np.array([[16, 16, 1.0]], np.float32)],
            {"feature_stride": 4, "scales": (8,), "ratios": (0.5, 1, 2),
             "rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4,
             "rpn_min_size": 1}),
        "_contrib_boolean_mask": ([_fn(4, 3),
                                   np.array([1, 0, 1, 1], np.float32)], {}),
        "_contrib_box_encode": (
            [np.ones((1, 4), np.float32), _idx(4, 1, 4),
             _RNG.uniform(0.1, 0.4, (1, 4, 4)).astype(np.float32),
             _RNG.uniform(0.1, 0.4, (1, 4, 4)).astype(np.float32)], {}),
        "_contrib_calibrate_entropy": (
            [np.maximum(_RNG.poisson(5, 64), 0).astype(np.float32),
             np.linspace(-4, 4, 65).astype(np.float32)], {}),
        "_contrib_hawkesll": (
            [_f(3), _f(3) * 0.3, _f(3), _RNG.exponential(1, (2, 5))
             .astype(np.float32), _idx(3, 2, 5),
             np.full(2, 5, np.float32), np.full(2, 6.0, np.float32)], {}),
        "_contrib_interleaved_matmul_selfatt_qk": (
            [_fn(T, B, 3 * 2 * 4)], {"heads": 2}),
        "_contrib_interleaved_matmul_selfatt_valatt": (
            [_fn(T, B, 3 * 2 * 4), _f(B * 2, T, T)], {"heads": 2}),
        "_contrib_interleaved_matmul_encdec_qk": (
            [_fn(T, B, 2 * 4), _fn(T, B, 2 * 2 * 4)], {"heads": 2}),
        "_contrib_interleaved_matmul_encdec_valatt": (
            [_fn(T, B, 2 * 2 * 4), _f(B * 2, T, T)], {"heads": 2}),
        "_contrib_quantized_conv": (
            [(_RNG.randint(-100, 100, (B, C, H, W))).astype(np.int8),
             (_RNG.randint(-100, 100, (8, C, 3, 3))).astype(np.int8),
             (_RNG.randint(-100, 100, (8,))).astype(np.int8),
             np.float32(-1), np.float32(1), np.float32(-1), np.float32(1),
             np.float32(-1), np.float32(1)],
            {"kernel": (3, 3), "num_filter": 8, "pad": (1, 1)}),
        "_contrib_quantized_fully_connected": (
            [(_RNG.randint(-100, 100, (B, 6))).astype(np.int8),
             (_RNG.randint(-100, 100, (5, 6))).astype(np.int8),
             (_RNG.randint(-100, 100, (5,))).astype(np.int8),
             np.float32(-1), np.float32(1), np.float32(-1), np.float32(1),
             np.float32(-1), np.float32(1)],
            {"num_hidden": 5}),
        "_image_resize": ([(_RNG.rand(8, 8, 3) * 255).astype(np.uint8)],
                          {"size": (4, 4)}),
        "_linalg_maketrian": ([_fn(1, 6)], {}),
        "_np_moveaxis": ([_fn(2, 3, 4)], {"source": 0, "destination": 2}),
        "_np_roll": ([_fn(3, 4)], {"shift": 1}),
        "_np_unique": ([_idx(5, 12)], {}),
        "_npi_bincount": ([_idx(6, 10).astype(np.int32)], {}),
        "_npi_bitwise_not": ([_idx(6, 3, 4).astype(np.int32)], {}),
        "_npi_bitwise_or": ([_idx(6, 3, 4).astype(np.int32),
                             _idx(6, 3, 4).astype(np.int32)], {}),
        "_npi_bitwise_or_scalar": ([_idx(6, 3, 4).astype(np.int32)],
                                   {"scalar": 3}),
        "_npi_bitwise_xor": ([_idx(6, 3, 4).astype(np.int32),
                              _idx(6, 3, 4).astype(np.int32)], {}),
        "_npi_bitwise_xor_scalar": ([_idx(6, 3, 4).astype(np.int32)],
                                    {"scalar": 3}),
        "_npi_choice": ([], {"a": 10, "size": (4,), "key": "sweep"}),
        "_npi_delete": ([_fn(5, 3)], {"obj": 1, "axis": 0}),
        "_npi_einsum": ([_fn(3, 4), _fn(4, 5)],
                        {"subscripts": "ij,jk->ik"}),
        "_npi_lcm": ([_idx(6, 3).astype(np.int32) + 1,
                      _idx(6, 3).astype(np.int32) + 1], {}),
        "_npi_lcm_scalar": ([_idx(6, 3).astype(np.int32) + 1],
                            {"scalar": 4}),
        "_npi_svd": ([_fn(4, 3)], {}),
        "_npi_tensorinv": ([(_fn(6, 6) + np.eye(6, dtype=np.float32) * 4)
                            .reshape(2, 3, 2, 3)], {"ind": 2}),
        "_npi_tensorsolve": ([_fn(3, 3) + np.eye(3, dtype=np.float32) * 3,
                              _fn(3)], {}),
        "_ravel_multi_index": ([_idx(3, 2, 4)], {"shape": (4, 4)}),
        "_sample_unique_zipfian": ([], {"range_max": 20, "shape": (1, 5)}),
        "_unravel_index": ([_idx(12, 4)], {"shape": (4, 4)}),
        "col2im": ([_fn(B, C * 4, 16)],
                   {"output_size": (8, 8), "kernel": (2, 2),
                    "stride": (2, 2)}),
        "im2col": ([x4], {"kernel": (2, 2), "stride": (2, 2)}),
        "multi_sgd_update": ([_fn(3, 4), _fn(3, 4), _fn(2, 3), _fn(2, 3)],
                             {"lrs": (0.1, 0.1), "wds": (0.0, 0.0),
                              "num_weights": 2}),
        "multi_sgd_mom_update": (
            [_fn(3, 4), _fn(3, 4), _fn(3, 4), _fn(2, 3), _fn(2, 3),
             _fn(2, 3)],
            {"lrs": (0.1, 0.1), "wds": (0.0, 0.0), "num_weights": 2}),
        "multi_mp_sgd_update": (
            [_fn(3, 4), _fn(3, 4), _fn(3, 4).astype(np.float32),
             _fn(2, 3), _fn(2, 3), _fn(2, 3)],
            {"lrs": (0.1, 0.1), "wds": (0.0, 0.0), "num_weights": 2}),
        "multi_mp_sgd_mom_update": (
            [_fn(3, 4), _fn(3, 4), _fn(3, 4), _fn(3, 4),
             _fn(2, 3), _fn(2, 3), _fn(2, 3), _fn(2, 3)],
            {"lrs": (0.1, 0.1), "wds": (0.0, 0.0), "num_weights": 2}),
        # domain-restricted elementwise ops
        "arcsin": ([_RNG.uniform(-0.9, 0.9, (3, 4)).astype(np.float32)], {}),
        "arccos": ([_RNG.uniform(-0.9, 0.9, (3, 4)).astype(np.float32)], {}),
        "arctanh": ([_RNG.uniform(-0.9, 0.9, (3, 4)).astype(np.float32)],
                    {}),
        "erfinv": ([_RNG.uniform(-0.9, 0.9, (3, 4)).astype(np.float32)], {}),
        "arccosh": ([_RNG.uniform(1.1, 3.0, (3, 4)).astype(np.float32)], {}),
        "_npi_arcsin": ([_RNG.uniform(-0.9, 0.9, (3, 4))
                         .astype(np.float32)], {}),
        "_npi_arccos": ([_RNG.uniform(-0.9, 0.9, (3, 4))
                         .astype(np.float32)], {}),
        "_npi_arctanh": ([_RNG.uniform(-0.9, 0.9, (3, 4))
                          .astype(np.float32)], {}),
        "_npi_arccosh": ([_RNG.uniform(1.1, 3.0, (3, 4))
                          .astype(np.float32)], {}),
        # optimizer updates with positivity-constrained state
        "rmspropalex_update": ([_fn(3, 4), _fn(3, 4), _f(3, 4) + 1,
                                np.zeros((3, 4), np.float32),
                                np.zeros((3, 4), np.float32)], {"lr": 0.1}),
        "rmsprop_update": ([_fn(3, 4), _fn(3, 4), _f(3, 4)], {"lr": 0.1}),
        # square / SPD linalg inputs
        "_linalg_extracttrian": ([_fn(4, 4)], {}),
        "_linalg_potrf": ([(lambda m: (m @ m.T
                                       + 4 * np.eye(4)).astype(np.float32))
                           (_fn(4, 4))], {}),
        # control flow + Custom take python-function/registered-op attrs —
        # covered by tests/test_control_flow.py and tests/test_custom_op.py
        "_cond": None,
        "_foreach": None,
        "_while_loop": None,
    }
    return h


# generic candidates tried in order when no hint exists
def _candidates(n_inputs):
    outs = []
    if n_inputs == 0:
        outs.append(([], {"shape": (2, 3)}))
        outs.append(([], {}))
    shapes2 = [(3, 4)] * max(n_inputs, 1)
    outs.append(([_f(*s) for s in shapes2], {}))
    outs.append(([_fn(*s) for s in shapes2], {}))
    outs.append(([_f(3, 4, 5)[0] if False else _f(4,)
                  for _ in range(max(n_inputs, 1))], {}))
    outs.append(([_f(2, 3, 4, 4) for _ in range(max(n_inputs, 1))], {}))
    return outs


def build_cases(verbose=False):
    """Synthesize one concrete call per distinct registered op.

    Returns (cases, uncovered): cases maps op name -> (arrays, attrs);
    uncovered is the list of op names with no working synthesis.
    """
    from incubator_mxnet_tpu.ops import registry

    hints = _hints()
    seen = {}
    for name, op in registry.OPS.items():
        seen.setdefault(id(op), op)
    cases, uncovered = {}, []
    for op in seen.values():
        name = op.name
        if name in hints:
            if hints[name] is None:
                uncovered.append(name)
                continue
            cases[name] = hints[name]
            continue
        n = op.num_inputs if op.num_inputs is not None else 2
        got = None
        for arrays, attrs in _candidates(n):
            try:
                import jax

                avals = [jax.ShapeDtypeStruct(np.asarray(a).shape,
                                              np.asarray(a).dtype)
                         for a in arrays]
                if op.needs_rng:
                    attrs = dict(attrs)
                    attrs["key"] = jax.random.PRNGKey(0)
                op.infer(avals, **{k: v for k, v in attrs.items()})
                got = (arrays, attrs)
                break
            except Exception as e:  # noqa: BLE001 - synthesis probing
                if verbose:
                    print("  %s: %s" % (name, e), file=sys.stderr)
        if got is not None:
            cases[name] = got
        else:
            uncovered.append(name)
    return cases, sorted(uncovered)


if __name__ == "__main__":
    sys.path.insert(0, "/root/repo")
    cases, uncovered = build_cases(verbose="-v" in sys.argv)
    print("covered: %d  uncovered: %d" % (len(cases), len(uncovered)))
    for n in uncovered:
        print("  MISSING", n)
