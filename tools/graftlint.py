#!/usr/bin/env python
"""graftlint CLI — source-level (Level 2) static analysis gate.

Walks the given paths (default: ``incubator_mxnet_tpu/``) and reports
idiom violations that break sharded-program discipline:

- GL101  shard_map imported from jax directly (the one version-compat
         home is ``incubator_mxnet_tpu/parallel/mesh.py``)
- GL102  host side effects (time.*, np.random.*, stdlib random) inside
         jit-decorated functions
- GL103  PartitionSpec entries built from f-strings or integer ranks

Exit status 1 when any error-severity finding remains (CI gate —
``tests/test_graftlint.py`` runs this over the package in tier-1).
Suppress a finding by appending ``# graftlint: disable[=GLxxx]`` to the
offending line.  Trace-time (Level 1) checks run inside
``make_train_step(lint=...)`` / ``MXTPU_LINT`` — see docs/ANALYSIS.md.

Usage::

    python tools/graftlint.py [paths...] [--min-severity warning]
"""
from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_ROOT, "incubator_mxnet_tpu")],
                    help="files/directories to lint (default: the "
                         "incubator_mxnet_tpu package)")
    ap.add_argument("--min-severity", default="info",
                    choices=["info", "warning", "error"],
                    help="lowest severity to print (exit code always "
                         "keys off errors)")
    ap.add_argument("--suppress", default="",
                    help="comma-separated GLxxx codes to suppress")
    args = ap.parse_args(argv)

    from incubator_mxnet_tpu.analysis.diagnostics import Severity
    from incubator_mxnet_tpu.analysis.source_lint import lint_paths

    suppress = tuple(c.strip() for c in args.suppress.split(",")
                     if c.strip())
    report = lint_paths(args.paths, suppress=suppress)
    out = report.format(Severity[args.min_severity.upper()])
    if out:
        print(out)
    n_err = len(report.errors)
    print("graftlint: %d file finding(s), %d error(s)"
          % (len(report), n_err))
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
