#!/usr/bin/env python
"""graftlint CLI — source-level (Level 2) static analysis gate.

Walks the given paths (default: ``incubator_mxnet_tpu/``) and reports
idiom violations that break sharded-program discipline:

- GL101  shard_map imported from jax directly (the one version-compat
         home is ``incubator_mxnet_tpu/parallel/mesh.py``)
- GL102  host side effects (time.*, np.random.*, stdlib random) inside
         jit-decorated functions
- GL103  PartitionSpec entries built from f-strings or integer ranks

Exit status 1 when any error-severity finding remains (CI gate —
``tests/test_graftlint.py`` runs this over the package in tier-1).
Suppress a finding by appending ``# graftlint: disable[=GLxxx]`` to the
offending line.  Trace-time (Level 1) checks run inside
``make_train_step(lint=...)`` / ``MXTPU_LINT`` — see docs/ANALYSIS.md.

``--select``/``--ignore`` filter by diagnostic code so CI can gate on a
precise code set (e.g. ``--select GL101,GL102`` hard-fails import/side-
effect idiom while other codes stay advisory); ``--ignore``d codes are
dropped from both the report and the exit status.  Both accept
``GL2*``-style prefix globs (``fnmatch``), the same grammar
``lint_suppress=`` honors, so a whole code family can be gated or
silenced at once.

``--format=json`` prints the stable machine schema (one object:
``{"version", "tool", "findings": [{code, severity, message, where,
hint}], "summary": {total, errors, warnings}}``) so CI and the future
autotuner consume lint output programmatically; severity is serialized
by NAME.

``--format=sarif`` prints SARIF 2.1.0 (the static-analysis interchange
format GitHub code scanning and other CI UIs ingest): one ``run`` with
the graftlint driver, one ``rules`` entry per distinct code (summary
from the stable catalog), one ``result`` per finding with
``path``/``startLine`` parsed out of ``where``.  Schema-shape is
validated in ``tests/test_graftlint.py``.

``--ranges MODEL`` (dense | conv-bn | resnet50) traces the named
model's inference program and prints the graftrange per-var value-range
table (``analysis/value_range.py``) with any GL4xx findings merged
into the report — the numerics companion to the source-level walk.

Usage::

    python tools/graftlint.py [paths...] [--min-severity warning]
                              [--select GL101,GL103] [--ignore GL2*]
                              [--format json|sarif] [--ranges conv-bn]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _sarif_level(sev) -> str:
    """Severity -> SARIF result.level (error/warning/note)."""
    name = str(sev).lower()
    return {"error": "error", "warning": "warning"}.get(name, "note")


def _sarif_location(where: str):
    """Parse a ``path:line`` ``where`` into a SARIF physicalLocation
    (None for trace-level findings with no source anchor)."""
    path, sep, line = (where or "").rpartition(":")
    if not sep or not line.isdigit() or not path:
        return None
    uri = os.path.relpath(path, _ROOT) if os.path.isabs(path) else path
    return {"physicalLocation": {
        "artifactLocation": {"uri": uri.replace(os.sep, "/")},
        "region": {"startLine": int(line)}}}


def to_sarif(report) -> dict:
    """One SARIF 2.1.0 log for a LintReport — the shape CI
    code-scanning UIs ingest (``--format sarif``)."""
    from incubator_mxnet_tpu.analysis.diagnostics import CODES

    rule_ids = sorted({d.code for d in report})
    rules = []
    for code in rule_ids:
        default = CODES.get(code)
        rules.append({
            "id": code,
            "shortDescription": {
                "text": default[1] if default else code},
            "defaultConfiguration": {
                "level": _sarif_level(default[0]) if default
                else "warning"},
        })
    index = {c: i for i, c in enumerate(rule_ids)}
    results = []
    for d in report:
        res = {"ruleId": d.code, "ruleIndex": index[d.code],
               "level": _sarif_level(d.severity),
               "message": {"text": d.message + (
                   ("\nhint: " + d.hint) if d.hint else "")}}
        loc = _sarif_location(d.where)
        if loc is not None:
            res["locations"] = [loc]
        results.append(res)
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://github.com/apache/incubator-mxnet",
                "version": "1.0.0",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_ROOT, "incubator_mxnet_tpu")],
                    help="files/directories to lint (default: the "
                         "incubator_mxnet_tpu package)")
    ap.add_argument("--min-severity", default="info",
                    choices=["info", "warning", "error"],
                    help="lowest severity to print (exit code always "
                         "keys off errors)")
    ap.add_argument("--suppress", default="",
                    help="comma-separated GLxxx codes to suppress "
                         "(alias of --ignore, kept for compatibility)")
    ap.add_argument("--select", default="",
                    help="comma-separated GLxxx codes or GL2*-style "
                         "prefix globs: report ONLY these (the exit "
                         "code keys off errors among them)")
    ap.add_argument("--ignore", default="",
                    help="comma-separated GLxxx codes or prefix globs to "
                         "drop from the report and the exit status")
    ap.add_argument("--format", dest="fmt", default="text",
                    choices=["text", "json", "sarif"],
                    help="json: the stable Diagnostic schema for CI / "
                         "autotuner consumption; sarif: SARIF 2.1.0 "
                         "for code-scanning UIs")
    ap.add_argument("--ranges", metavar="MODEL", default=None,
                    choices=["dense", "conv-bn", "resnet50"],
                    help="additionally trace this model and report the "
                         "graftrange per-var value-range table + GL4xx "
                         "findings (analysis/value_range.py)")
    args = ap.parse_args(argv)

    from incubator_mxnet_tpu.analysis.diagnostics import (LintReport,
                                                          Severity,
                                                          code_matches)
    from incubator_mxnet_tpu.analysis.source_lint import lint_paths

    def _codes(s):
        return tuple(c.strip() for c in s.split(",") if c.strip())

    select = _codes(args.select)
    ignore = _codes(args.ignore) + _codes(args.suppress)
    report = lint_paths(args.paths)
    range_report = None
    if args.ranges:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_graftpass_cli", os.path.join(_ROOT, "tools",
                                           "graftpass.py"))
        gp = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(gp)
        from incubator_mxnet_tpu.analysis.value_range import \
            analyze_ranges

        # the ONE trace-and-seed block (model build, observed-extrema
        # seeding, abstract trace) lives in tools/graftpass.py
        closed, seeds, labels = gp.trace_model_program(args.ranges)[:3]
        range_report = analyze_ranges(closed, input_ranges=seeds,
                                      invar_labels=labels)
        report = LintReport(list(report)
                            + list(range_report.diagnostics))
    kept = [d for d in report
            if (not select or any(code_matches(d.code, p) for p in select))
            and not any(code_matches(d.code, p) for p in ignore)]
    report = LintReport(kept)
    n_err = len(report.errors)
    if args.fmt == "sarif":
        print(json.dumps(to_sarif(report), indent=2))
        return 1 if n_err else 0
    if args.fmt == "json":
        print(json.dumps({
            "version": 1,
            "tool": "graftlint",
            "findings": [d.to_dict() for d in report],
            "summary": {"total": len(report), "errors": n_err,
                        "warnings": len(report.warnings)},
        }, indent=2))
        return 1 if n_err else 0
    out = report.format(Severity[args.min_severity.upper()])
    if out:
        print(out)
    if range_report is not None:
        # rows only: the diagnostics were already merged into the main
        # report above (where --select/--ignore filtering applies)
        print("\ngraftrange per-var table (%s):" % args.ranges)
        print(range_report.format(include_diagnostics=False))
    print("graftlint: %d file finding(s), %d error(s)"
          % (len(report), n_err))
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
