#!/usr/bin/env python
"""graftlint CLI — source-level (Level 2) static analysis gate.

Walks the given paths (default: ``incubator_mxnet_tpu/``) and reports
idiom violations that break sharded-program discipline:

- GL101  shard_map imported from jax directly (the one version-compat
         home is ``incubator_mxnet_tpu/parallel/mesh.py``)
- GL102  host side effects (time.*, np.random.*, stdlib random) inside
         jit-decorated functions
- GL103  PartitionSpec entries built from f-strings or integer ranks

Exit status 1 when any error-severity finding remains (CI gate —
``tests/test_graftlint.py`` runs this over the package in tier-1).
Suppress a finding by appending ``# graftlint: disable[=GLxxx]`` to the
offending line.  Trace-time (Level 1) checks run inside
``make_train_step(lint=...)`` / ``MXTPU_LINT`` — see docs/ANALYSIS.md.

``--select``/``--ignore`` filter by diagnostic code so CI can gate on a
precise code set (e.g. ``--select GL101,GL102`` hard-fails import/side-
effect idiom while other codes stay advisory); ``--ignore``d codes are
dropped from both the report and the exit status.  Both accept
``GL2*``-style prefix globs (``fnmatch``), the same grammar
``lint_suppress=`` honors, so a whole code family can be gated or
silenced at once.

``--format=json`` prints the stable machine schema (one object:
``{"version", "tool", "findings": [{code, severity, message, where,
hint}], "summary": {total, errors, warnings}}``) so CI and the future
autotuner consume lint output programmatically; severity is serialized
by NAME.

Usage::

    python tools/graftlint.py [paths...] [--min-severity warning]
                              [--select GL101,GL103] [--ignore GL2*]
                              [--format json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_ROOT, "incubator_mxnet_tpu")],
                    help="files/directories to lint (default: the "
                         "incubator_mxnet_tpu package)")
    ap.add_argument("--min-severity", default="info",
                    choices=["info", "warning", "error"],
                    help="lowest severity to print (exit code always "
                         "keys off errors)")
    ap.add_argument("--suppress", default="",
                    help="comma-separated GLxxx codes to suppress "
                         "(alias of --ignore, kept for compatibility)")
    ap.add_argument("--select", default="",
                    help="comma-separated GLxxx codes or GL2*-style "
                         "prefix globs: report ONLY these (the exit "
                         "code keys off errors among them)")
    ap.add_argument("--ignore", default="",
                    help="comma-separated GLxxx codes or prefix globs to "
                         "drop from the report and the exit status")
    ap.add_argument("--format", dest="fmt", default="text",
                    choices=["text", "json"],
                    help="json: the stable Diagnostic schema for CI / "
                         "autotuner consumption")
    args = ap.parse_args(argv)

    from incubator_mxnet_tpu.analysis.diagnostics import (LintReport,
                                                          Severity,
                                                          code_matches)
    from incubator_mxnet_tpu.analysis.source_lint import lint_paths

    def _codes(s):
        return tuple(c.strip() for c in s.split(",") if c.strip())

    select = _codes(args.select)
    ignore = _codes(args.ignore) + _codes(args.suppress)
    report = lint_paths(args.paths)
    kept = [d for d in report
            if (not select or any(code_matches(d.code, p) for p in select))
            and not any(code_matches(d.code, p) for p in ignore)]
    report = LintReport(kept)
    n_err = len(report.errors)
    if args.fmt == "json":
        print(json.dumps({
            "version": 1,
            "tool": "graftlint",
            "findings": [d.to_dict() for d in report],
            "summary": {"total": len(report), "errors": n_err,
                        "warnings": len(report.warnings)},
        }, indent=2))
        return 1 if n_err else 0
    out = report.format(Severity[args.min_severity.upper()])
    if out:
        print(out)
    print("graftlint: %d file finding(s), %d error(s)"
          % (len(report), n_err))
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
