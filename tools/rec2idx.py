#!/usr/bin/env python
"""Recreate the .idx random-access index for an existing .rec file
(reference: tools/rec2idx.py IndexCreator over MXRecordIO).

Usage: python tools/rec2idx.py data.rec data.idx
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from incubator_mxnet_tpu.recordio import MXRecordIO


def create_index(rec_path: str, idx_path: str, key_type=int) -> int:
    """Walk the record stream and write ``key\\tbyte-offset`` per record
    (the MXIndexedRecordIO index contract); returns the record count."""
    rec = MXRecordIO(rec_path, "r")
    n = 0
    with open(idx_path, "w") as fidx:
        while True:
            pos = rec.tell()
            if rec.read() is None:
                break
            fidx.write("%s\t%d\n" % (key_type(n), pos))
            n += 1
    rec.close()
    return n


def main():
    ap = argparse.ArgumentParser(
        description="Create an index file for a RecordIO file")
    ap.add_argument("record", help="path to the .rec file")
    ap.add_argument("index", help="path to the .idx file to write")
    args = ap.parse_args()
    n = create_index(args.record, args.index)
    print("wrote %d index entries to %s" % (n, args.index))


if __name__ == "__main__":
    main()
