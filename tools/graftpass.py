#!/usr/bin/env python
"""graftpass CLI — run the verified jaxpr→jaxpr rewrite pipeline on a
model and print the receipts.

Builds the requested model, traces its inference program, runs the
given pass pipeline through the :class:`~analysis.passes.PassManager`
— abstract eval, re-lint (GL302), graftcost before/after receipts
(GL303), seeded concrete probe (GL301) — and reports one receipt per
pass: contract, rewrite hits, predicted FLOPs/HBM-bytes/param-bytes
before/after, probe verdict.  No XLA ahead-of-time compile is ever
paid: refused rewrites cost nothing, and the probes run eagerly.

Exit status 1 on a contract violation (GL301) or re-lint failure
(GL302) — the CI gate shape ``tools/graftlint.py`` set; 0 otherwise
(GL303 skipped-rewrite warnings do not gate).

``--format json`` prints the stable machine schema::

    {"version": 1, "tool": "graftpass", "model": ..., "passes":
     [<receipt>...], "diagnostics": [<Diagnostic>...],
     "summary": {"installed": n, "refused": n, "errors": n}}

Under graftsched, ``--schedule FILE`` replaces the on/off ``--passes``
list with a per-site decision vector (the canonical JSON the
train-schedule autotuner persists under ``knobs.schedule``); receipts
then carry one row per site — decision, installed/excluded verdict,
attributed FLOPs/HBM deltas.  ``--list-sites`` prints the addressable
sites of the traced model, and ``--format sarif`` emits the receipts'
diagnostics in the SARIF 2.1.0 shape ``tools/graftlint.py`` defined.

Usage::

    python tools/graftpass.py --list
    python tools/graftpass.py --model dense --passes quantize_int8,cse_dead_aux
    python tools/graftpass.py --model conv-bn --passes space_to_depth \
        --batch 8 --format json
    python tools/graftpass.py --model resnet50 --passes space_to_depth \
        --no-probe
    python tools/graftpass.py --model conv-bn --passes amp_bf16 --list-sites
    python tools/graftpass.py --model conv-bn --schedule winner.json \
        --format sarif
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _range_arg(s):
    """argparse type for --input-range (shared grammar:
    analysis.value_range.parse_range_arg)."""
    from incubator_mxnet_tpu.analysis.value_range import parse_range_arg

    try:
        return parse_range_arg(s)
    except ValueError as e:
        raise argparse.ArgumentTypeError("--input-range %s" % e)


def _build_model(name):
    """(net, sample_shape): dense = the test MLP; conv-bn = a conv1-
    style 7x7/s2 stem + conv-BN block (a space_to_depth target);
    resnet50 = the flagship (heavy: probe it with --no-probe off-CI)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    if name == "dense":
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 16)))
        return net, (16,)
    if name == "conv-bn":
        net = nn.HybridSequential()
        net.add(nn.Conv2D(16, 7, strides=2, padding=3, in_channels=3))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2D(16, 3, padding=1, in_channels=16))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.initialize(init=mx.init.Xavier())
        net(nd.ones((2, 3, 16, 16)))
        return net, (3, 16, 16)
    if name == "resnet50":
        from incubator_mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet50_v1(classes=1000)
        net.initialize(init=mx.init.Zero())
        net.shape_init((1, 3, 224, 224))
        return net, (3, 224, 224)
    raise SystemExit("unknown --model %r (dense, conv-bn, resnet50)" % name)


def trace_model_program(model, batch=8, input_range=None,
                        seed_observed=True):
    """Build a named model, abstractly trace its inference program and
    assemble the graftrange seeds/labels (observed param extrema via
    ``analysis.value_range.observed_range`` + the declared input
    range) — the ONE trace-and-seed block shared by ``graftpass
    --ranges`` and ``graftlint --ranges``.  Returns ``(closed, seeds,
    labels, net, params, p_vals, sample_shape)``."""
    import numpy as np

    import jax

    from incubator_mxnet_tpu.analysis.value_range import observed_range
    from incubator_mxnet_tpu.gluon.block import pure_forward

    net, sample_shape = _build_model(model)
    params = list(net.collect_params().values())
    p_vals = [p._data._data for p in params]

    def infer(pv, x):
        out, _tc = pure_forward(net, params, pv, x, training=False)
        return out

    x = jax.ShapeDtypeStruct((batch,) + tuple(sample_shape), np.float32)
    closed = jax.make_jaxpr(infer)(
        [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in p_vals], x)
    seeds, labels = {}, {}
    for i, (prm, v) in enumerate(zip(params, p_vals)):
        labels[i] = "param:%s" % prm.name
        if seed_observed:
            seed = observed_range(v)
            if seed is not None:
                seeds[i] = seed
    labels[len(p_vals)] = "x"
    if input_range is not None:
        seeds[len(p_vals)] = tuple(input_range)
    return closed, seeds, labels, net, params, p_vals, sample_shape


def _list_registry(fmt):
    from incubator_mxnet_tpu.analysis.passes import PASS_REGISTRY, get_pass

    rows = []
    for name in sorted(PASS_REGISTRY):
        p = get_pass(name)
        rows.append({"name": name, "contract": p.contract.describe(),
                     "description": p.description})
    if fmt == "json":
        print(json.dumps({"version": 1, "tool": "graftpass",
                          "registry": rows}, indent=2))
    else:
        for r in rows:
            print("%-16s %-28s %s" % (r["name"], r["contract"],
                                      r["description"]))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftpass", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--list", action="store_true",
                    help="print the pass registry and exit")
    ap.add_argument("--model", default="dense",
                    choices=["dense", "conv-bn", "resnet50"])
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--passes", default="quantize_int8,cse_dead_aux",
                    help="comma-separated registry names, applied in "
                         "order")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the concrete probe (abstract eval, "
                         "re-lint and cost receipts still gate)")
    ap.add_argument("--ranges", action="store_true",
                    help="print the graftrange per-var value-range "
                         "table of the traced model program "
                         "(analysis/value_range.py) next to the "
                         "receipts; also enables the amp_bf16 GL403 "
                         "gate (numerics='warn')")
    ap.add_argument("--numerics", default=None,
                    choices=["off", "warn", "error"],
                    help="graftrange mode for range-gated passes "
                         "(default: 'warn' with --ranges, else 'off')")
    ap.add_argument("--input-range", default=None, type=_range_arg,
                    help="declared input value range 'lo,hi' seeding "
                         "the range analysis (default: observed from "
                         "the model's initialized params only)")
    ap.add_argument("--device", default="tpu-v5e",
                    help="graftcost roofline device-spec registry key")
    ap.add_argument("--schedule", default=None, metavar="FILE",
                    help="JSON PassSchedule (the canonical dict "
                         "autotune's train-schedule winner carries "
                         "under knobs.schedule): per-site decisions "
                         "replace the --passes on/off list; receipts "
                         "report every site's decision + verdict")
    ap.add_argument("--list-sites", action="store_true",
                    help="enumerate the applicable sites of --passes/"
                         "--schedule on the traced model and exit "
                         "(the addressing a schedule's site ids use)")
    ap.add_argument("--format", dest="fmt", default="table",
                    choices=["table", "json", "sarif"])
    args = ap.parse_args(argv)

    if args.list:
        return _list_registry(args.fmt)

    from incubator_mxnet_tpu.analysis import LintError, Severity
    from incubator_mxnet_tpu.analysis.passes import (PassContext,
                                                     PassManager,
                                                     PassSchedule)

    numerics = args.numerics or ("warn" if args.ranges else "off")
    closed, seeds, labels, net, params, p_vals, sample_shape = \
        trace_model_program(args.model, batch=args.batch,
                            input_range=args.input_range,
                            seed_observed=numerics != "off")
    input_ranges = seeds if numerics != "off" else None
    ctx = PassContext(
        param_invars=frozenset(range(len(p_vals))),
        probe="off" if args.no_probe else "auto",
        probe_overrides=dict(enumerate(p_vals)),
        numerics=numerics,
        input_ranges=input_ranges,
        where="graftpass CLI (%s)" % args.model)
    schedule = None
    if args.schedule:
        try:
            with open(args.schedule) as f:
                schedule = PassSchedule.from_dict(json.load(f))
        except (OSError, ValueError, KeyError) as e:
            print("graftpass: --schedule %s: %s" % (args.schedule, e),
                  file=sys.stderr)
            return 2
    try:
        mgr = PassManager(None if schedule is not None else args.passes,
                          schedule=schedule, device=args.device,
                          raise_on_error=False)
        if args.list_sites:
            rows = []
            for p in mgr.passes:
                sites = (p.enumerate_sites(closed, ctx)
                         if p.site_aware else [])
                for s in sites:
                    rows.append({"pass": p.name, "site": s.id,
                                 "kind": s.kind, "detail": s.detail,
                                 "flops": s.flops,
                                 "hbm_bytes": s.hbm_bytes})
                if not sites:
                    rows.append({"pass": p.name, "site": None,
                                 "kind": "whole-program"
                                 if not p.site_aware else "none",
                                 "detail": "", "flops": 0.0,
                                 "hbm_bytes": 0.0})
            if args.fmt == "table":
                for r in rows:
                    print("%-16s %-24s %-14s %s"
                          % (r["pass"], r["site"] or "-", r["kind"],
                             r["detail"]))
            else:
                print(json.dumps({"version": 1, "tool": "graftpass",
                                  "model": args.model,
                                  "batch": args.batch, "sites": rows},
                                 indent=2))
            return 0
        result = mgr.run(closed, ctx)
    except (ValueError, LintError) as e:
        print("graftpass: %s" % e, file=sys.stderr)
        return 1
    errors = [d for d in result.diagnostics
              if d.severity >= Severity.ERROR]
    range_report = None
    if args.ranges:
        from incubator_mxnet_tpu.analysis.value_range import \
            analyze_ranges

        range_report = analyze_ranges(closed,
                                      input_ranges=input_ranges,
                                      invar_labels=labels)
    active_sched = schedule or (PassSchedule.from_passes(mgr.passes)
                                if mgr.passes else None)
    payload = {
        "version": 1,
        "tool": "graftpass",
        "model": args.model,
        "batch": args.batch,
        "device": args.device,
        "schedule": None if active_sched is None else {
            "hash": active_sched.hash(),
            "canonical": active_sched.canonical()},
        "passes": [r.to_dict() for r in result.receipts],
        "diagnostics": [d.to_dict() for d in result.diagnostics],
        "summary": {
            "installed": sum(1 for r in result.receipts if r.installed),
            "refused": sum(1 for r in result.receipts
                           if r.changed and not r.installed),
            "errors": len(errors)},
    }
    if range_report is not None:
        payload["ranges"] = range_report.to_dict()
    if args.fmt == "sarif":
        # the PR-13 emitter: receipts' diagnostics as SARIF results,
        # the shape CI code-scanning ingests (same schema graftlint
        # --format sarif emits)
        from tools.graftlint import to_sarif

        print(json.dumps(to_sarif(list(result.diagnostics)), indent=2))
    elif args.fmt == "json":
        print(json.dumps(payload, indent=2))
    else:
        print("graftpass[%s batch=%d]: %d pass(es), %d installed, "
              "%d refused"
              % (args.model, args.batch, len(result.receipts),
                 payload["summary"]["installed"],
                 payload["summary"]["refused"]))
        print("%-16s %-26s %-9s %5s %12s %12s %10s"
              % ("pass", "contract", "installed", "hits",
                 "HBM MB before", "after", "param KB"))
        for r in result.receipts:
            print("%-16s %-26s %-9s %5d %12.3f %12.3f %6.1f->%.1f"
                  % (r.name, r.contract, str(r.installed), r.hits,
                     r.hbm_bytes_before / 1e6, r.hbm_bytes_after / 1e6,
                     r.param_bytes_before / 1e3,
                     r.param_bytes_after / 1e3))
            if r.probe is not None:
                print("    probe: %s" % json.dumps(r.probe))
            if r.notes:
                print("    %s" % r.notes)
            for s in r.sites or ():
                verdict = ("excluded: %s" % s["excluded"]
                           if s["excluded"] else
                           "installed" if s["installed"] else "skipped")
                print("    site %-18s %-4s %+12.1f B  %s  %s"
                      % (s["site"], "on" if s["decision"] else "off",
                         s["hbm_bytes_delta"], verdict, s["detail"]))
        for d in result.diagnostics:
            print(d.format())
        if range_report is not None:
            print("\ngraftrange per-var table (%s batch=%d):"
                  % (args.model, args.batch))
            print(range_report.format())
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
