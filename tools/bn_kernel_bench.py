#!/usr/bin/env python
"""Per-kernel DMA-efficiency benchmark for the fused ghost-BN kernels.

For every ResNet-50 BN shape (batch 256) this measures, on the chip:

* ``copy``   — a Pallas copy kernel using the SAME view, BlockSpec
  blocks and grid as the selected fwd kernel (whole-L, lane-fold or
  spatial-tiled): the pure-DMA ceiling for that plan.  If ``copy``
  sustains ~roofline but ``fwd`` doesn't, compute (VPU) binds; if
  ``copy`` itself is slow, the window DMA pattern binds (strided runs
  / padding) — this is the measurement VERDICT r4 asked for ("prove
  which Mosaic limit binds").
* ``fwd``    — the planned forward variant, one read of X per pass
  (the tiled form pays its extra stats pass and says so in the bytes).
* ``bwd``    — the planned backward variant (one-read whole-L /
  lane-fold, or the two-phase tiled gY-read-once protocol).
* ``stock_xla`` — the plain-jnp ghost BN (XLA's own fusions) on the
  same shape, fwd and fwd+bwd: the reference column every variant row
  is judged against.

One row per (shape, residual[, dual]) with the plan columns
(variant / bwd / fold / l_tile / window MB) so a chip log directly
shows WHICH kernel form produced each number.  ``--format json``
prints machine-readable JSON lines (the chip-queue artifact);
``--out`` appends the same rows to a file.

Reference bar: docs/PERF.md roofline (819 GB/s HBM peak on v5e);
the round-4 kernels sustained ~55 % — the round-5 full-C blocks must
show >= 85 % on ``copy`` for the fused path to be viable.
"""
import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax
import jax.numpy as jnp
import numpy as np

from incubator_mxnet_tpu.parallel import fused_bn as fb

HBM_PEAK_GBS = 819.0
GROUP = 16

SHAPES = [
    # (N, C, H, W) — every distinct BN shape in ResNet-50 v1 at batch 256
    (256, 64, 112, 112),
    (256, 64, 56, 56),
    (256, 256, 56, 56),
    (256, 128, 28, 28),
    (256, 512, 28, 28),
    (256, 256, 14, 14),
    (256, 1024, 14, 14),
    (256, 512, 7, 7),
    (256, 2048, 7, 7),
]

# interpret-mode shapes sized so the 104 MB-budget selection logic is
# reproduced at a small budget: one lane-fold row (C=32 < 128 at
# N=256), one spatial-tiled row, one whole-L fused row
DRY_BUDGET = 200000
DRY_SHAPES = [
    (256, 32, 4, 4),    # lane-fold (fold 4)
    (32, 128, 6, 6),    # spatial-tiled fwd+bwd
    (32, 128, 2, 2),    # whole-L fused
]


def _time(fn, *args, iters=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready")
        else a, out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready")
        else a, out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def _copy_kernel(x_ref, y_ref, *, lc):
    l = x_ref.shape[0]

    def body(i, _):
        sl = fb.pl.ds(i * jnp.int32(lc), lc)
        y_ref[sl] = x_ref[sl]
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(l // lc), body, jnp.int32(0))


def _call_copy(x_v, plan):
    """Pure-DMA ceiling with the selected variant's exact blocks/grid."""
    l = x_v.shape[0]
    if plan.ch_axis == 2:
        n, c = x_v.shape[1], x_v.shape[2] // plan.fold
    else:
        n, c = x_v.shape[2], x_v.shape[1]
    if plan.variant == "tiled":
        ng = plan.ab[0]
        xspec, _, _ = fb._tile_specs(plan.l_tile, ng, c)
        grid = (n // ng, l // plan.l_tile)
        lc = fb._chunk(plan.l_tile, ng, c)
    else:
        xspec, _, _, ngroups, _, _ = fb._specs(l, n, c, plan.ab,
                                               plan.ch_axis, plan.fold)
        grid = (ngroups,
                c // (plan.ab[1] if plan.ch_axis == 2 else plan.ab[0]))
        lc = fb._chunk(l, plan.ab[0],
                       plan.ab[1] * (plan.fold if plan.ch_axis == 2 else 1))
    kern = functools.partial(_copy_kernel, lc=lc)
    return fb.pl.pallas_call(
        kern, grid=grid, in_specs=[xspec], out_specs=xspec,
        out_shape=jax.ShapeDtypeStruct(x_v.shape, x_v.dtype),
        compiler_params=fb._CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=fb._VMEM_KERNEL_LIMIT),
        interpret=fb._use_interpret())(x_v)


def bench_shape(n, c, h, w, dtype, residual, dual, emit, iters, warmup):
    itemsize = jnp.dtype(dtype).itemsize
    tensor_gb = n * c * h * w * itemsize / 1e9
    plan = fb._plan(n, c, h * w, itemsize, GROUP, residual, False, dual)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(size=(n, c, h, w)).astype(np.float32),
                    dtype=dtype)
    gamma = jnp.ones((c,), jnp.float32)
    beta = jnp.zeros((c,), jnp.float32)
    res = x * 0.5 if residual else None

    row = {"shape": "%dx%dx%dx%d" % (n, c, h, w), "dtype": str(dtype),
           "residual": bool(residual), "dual": bool(dual),
           "variant": "jnp-fallback" if plan is None else plan.variant,
           "bwd_variant": "jnp" if plan is None else plan.bwd_variant,
           "fold": 0 if plan is None else plan.fold,
           "l_tile": 0 if plan is None else (plan.l_tile or 0),
           "l_tile_bwd": 0 if plan is None else (plan.l_tile_bwd or 0),
           "window_mb": 0.0 if plan is None
           else round(plan.window_bytes / 1e6, 2)}

    def gbs(key, ms, nbytes_gb):
        row[key + "_ms"] = round(ms, 3)
        row[key + "_gbs"] = round(nbytes_gb / (ms / 1e3), 1)
        row[key + "_pct_peak"] = round(
            100 * (nbytes_gb / (ms / 1e3)) / HBM_PEAK_GBS, 1)

    # stock-XLA reference columns (always measured)
    ref = jax.jit(functools.partial(fb._gbn_ref, eps=1e-3, act="relu",
                                    group=GROUP))
    ms = _time(ref, x, gamma, beta, res, iters=iters, warmup=warmup)
    gbs("stock_xla", ms, tensor_gb * (3 if residual else 2) + tensor_gb)

    def loss(xx, rr):
        y, _, _ = fb._gbn_ref(xx, gamma, beta, rr, 1e-3, "relu", GROUP)
        return (y.astype(jnp.float32) ** 2).sum()
    gref = jax.jit(jax.grad(loss, argnums=(0, 1) if residual else (0,)))
    ms = (_time(gref, x, res, iters=iters, warmup=warmup) if residual
          else _time(lambda a: gref(a, None), x, iters=iters,
                     warmup=warmup))
    gbs("stock_xla_fwd_bwd", ms, tensor_gb * (8 if residual else 6))

    if plan is None:
        emit(row)
        return

    x_v = fb._to_view(x, plan.ch_axis, plan.fold)
    res_v = None if res is None else fb._to_view(res, plan.ch_axis,
                                                 plan.fold)

    # pure-copy ceiling with the identical view/blocks/grid
    cp = jax.jit(functools.partial(_call_copy, plan=plan))
    ms = _time(cp, x_v, iters=iters, warmup=warmup)
    gbs("copy", ms, 2 * tensor_gb)

    # planned forward variant.  Tiled pays one extra read of X for the
    # cross-tile stats pass — charged in its bytes, exactly as
    # analysis/cost_model.py prices the two pallas_calls.
    if plan.variant == "tiled":
        fwd = jax.jit(functools.partial(
            fb._call_fwd_tiled, eps=1e-3, act="relu", ab=plan.ab,
            lt=plan.l_tile))
        fwd_gb = tensor_gb * (4 if residual else 3)
    else:
        fwd = jax.jit(functools.partial(
            fb._call_fwd, eps=1e-3, act="relu", ab=plan.ab,
            ch_axis=plan.ch_axis, fold=plan.fold))
        fwd_gb = tensor_gb * (3 if residual else 2)
    ms = _time(lambda a, r: fwd(a, gamma, beta, r), x_v, res_v,
               iters=iters, warmup=warmup)
    gbs("fwd", ms, fwd_gb)

    if plan.bwd_variant == "jnp":
        emit(row)
        return
    y_v, m, v = fwd(x_v, gamma, beta, res_v)
    gy_v = x_v * 0.1
    gy2_v = x_v * 0.3 if dual else None
    if plan.bwd_variant == "tiled":
        bwd = jax.jit(functools.partial(
            fb._call_bwd_tiled, eps=1e-3, act="relu", ab=plan.ab,
            lt=plan.l_tile_bwd))
        bwd_gb = tensor_gb * ((8 if dual else 7) if residual else 5)
    else:
        bwd = jax.jit(functools.partial(
            fb._call_bwd, eps=1e-3, act="relu", ab=plan.ab,
            ch_axis=plan.ch_axis, fold=plan.fold))
        bwd_gb = tensor_gb * ((6 if dual else 5) if residual else 3)
    ms = _time(lambda: bwd(gy_v, x_v, y_v if residual else None,
                           gamma, beta, m, v, gy2=gy2_v),
               iters=iters, warmup=warmup)
    gbs("bwd", ms, bwd_gb)
    emit(row)


COLS = ("shape", "residual", "dual", "variant", "bwd_variant", "fold",
        "l_tile", "window_mb", "copy_ms", "fwd_ms", "bwd_ms",
        "stock_xla_ms", "stock_xla_fwd_bwd_ms")


def _table_line(row):
    return " ".join("%*s" % (max(len(k), 8), row.get(k, "-"))
                    for k in COLS)


def main():
    global SHAPES
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--out", default=None, help="also append JSON rows here")
    ap.add_argument("--residual", action="store_true",
                    help="bench the residual variants too")
    ap.add_argument("--variants", action="store_true",
                    help="round-20 kernel-variant sweep: adds the "
                         "dual-cotangent residual rows (the tuple-"
                         "threaded block exits), so every kernel form — "
                         "whole-L, lane-fold, spatial-tiled, dual — "
                         "lands in the artifact")
    ap.add_argument("--format", dest="fmt", default="table",
                    choices=["table", "json"],
                    help="json prints one JSON object per row (the "
                         "chip-queue artifact format)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dry-run", action="store_true",
                    help="tiny shapes + a scaled-down VMEM budget in "
                         "interpret mode on CPU: exercises the lane-fold "
                         "/ tiled / fused selection and every kernel "
                         "call end-to-end (timings meaningless) — what "
                         "CHIP_QUEUE_DRY_RUN runs in tier-1")
    ap.add_argument("--self-test", action="store_true",
                    help="alias of --dry-run (kept for older queue logs)")
    args = ap.parse_args()
    iters, warmup = args.iters, 3
    if args.dry_run or args.self_test:
        SHAPES = DRY_SHAPES
        fb._WINDOW_BUDGET = DRY_BUDGET
        # never touch the (shared) chip in a dry run: pin the cpu
        # backend so _use_interpret() routes every kernel to interpret
        jax.config.update("jax_platforms", "cpu")
        iters, warmup = 1, 1
    sink = open(args.out, "a") if args.out else None

    def emit(row):
        line = json.dumps(row)
        if args.fmt == "json":
            print(line, flush=True)
        else:
            print(_table_line(row), flush=True)
        if sink:
            sink.write(line + "\n")
            sink.flush()

    backend = jax.default_backend()
    note = ("interpret mode (numbers are NOT kernel perf)"
            if backend != "tpu" else "on-chip")
    print("# backend=%s %s" % (backend, note), file=sys.stderr)
    if args.fmt == "table":
        print(" ".join("%*s" % (max(len(k), 8), k) for k in COLS),
              flush=True)
    dtype = jnp.dtype(args.dtype)
    want_res = args.residual or args.variants or args.dry_run
    for (n, c, h, w) in SHAPES:
        legs = [(False, False)]
        if want_res and c >= 128:
            legs.append((True, False))
            if args.variants or args.dry_run:
                legs.append((True, True))
        for residual, dual in legs:
            try:
                bench_shape(n, c, h, w, dtype, residual, dual, emit,
                            iters, warmup)
            except Exception as e:  # keep the sweep going; record why
                emit({"shape": "%dx%dx%dx%d" % (n, c, h, w),
                      "variant": "error", "stock_xla_ms": -1.0,
                      "residual": residual, "dual": dual,
                      "error": repr(e)[:300]})
    if sink:
        sink.close()


if __name__ == "__main__":
    main()
