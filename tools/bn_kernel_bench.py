#!/usr/bin/env python
"""Per-kernel DMA-efficiency benchmark for the fused ghost-BN kernels.

For every ResNet-50 BN shape (batch 256) this measures, on the chip:

* ``copy``   — a Pallas copy kernel using the SAME (L, A, B) view,
  BlockSpec blocks and grid as the fused fwd kernel: the pure-DMA
  ceiling for that plan.  If ``copy`` sustains ~roofline but ``fwd``
  doesn't, compute (VPU) binds; if ``copy`` itself is slow, the window
  DMA pattern binds (strided runs / padding) — this is the measurement
  VERDICT r4 asked for ("prove which Mosaic limit binds").
* ``fwd``    — fused stats+normalize+ReLU(+residual), one read of X.
* ``bwd``    — fused reductions+dX, one read of (dY, X[, Y]).
* ``xla``    — the plain-jnp ghost BN (XLA's own fusions) on the same
  shape, fwd and fwd+bwd, for the end-to-end comparison.

Prints one JSON line per measurement:
``{"shape": ..., "which": ..., "ms": ..., "gbs": ..., "pct_peak": ...}``

Reference bar: docs/PERF.md roofline (819 GB/s HBM peak on v5e);
the round-4 kernels sustained ~55 % — the round-5 full-C blocks must
show >= 85 % on ``copy`` for the fused path to be viable.
"""
import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax
import jax.numpy as jnp
import numpy as np

from incubator_mxnet_tpu.parallel import fused_bn as fb

HBM_PEAK_GBS = 819.0

SHAPES = [
    # (N, C, H, W) — every distinct BN shape in ResNet-50 v1 at batch 256
    (256, 64, 112, 112),
    (256, 64, 56, 56),
    (256, 256, 56, 56),
    (256, 128, 28, 28),
    (256, 512, 28, 28),
    (256, 256, 14, 14),
    (256, 1024, 14, 14),
    (256, 512, 7, 7),
    (256, 2048, 7, 7),
]


def _time(fn, *args, iters=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready")
        else a, out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda a: a.block_until_ready() if hasattr(a, "block_until_ready")
        else a, out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def _copy_kernel(x_ref, y_ref, *, lc):
    l = x_ref.shape[0]
    k = l // lc

    def body(i, _):
        sl = fb.pl.ds(i * jnp.int32(lc), lc)
        y_ref[sl] = x_ref[sl]
        return jnp.int32(0)
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(k), body, jnp.int32(0))


def _call_copy(x_v, ab, ch_axis):
    l = x_v.shape[0]
    n = x_v.shape[1] if ch_axis == 2 else x_v.shape[2]
    c = x_v.shape[2] if ch_axis == 2 else x_v.shape[1]
    xspec, _, _, ngroups, _, _ = fb._specs(l, n, c, ab, ch_axis)
    grid = (ngroups, c // (ab[1] if ch_axis == 2 else ab[0]))
    lc = fb._chunk(l, *ab)
    kern = functools.partial(_copy_kernel, lc=lc)
    return fb.pl.pallas_call(
        kern, grid=grid, in_specs=[xspec], out_specs=[xspec],
        out_shape=[jax.ShapeDtypeStruct(x_v.shape, x_v.dtype)],
        compiler_params=fb.pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=fb._VMEM_KERNEL_LIMIT),
        interpret=fb._use_interpret())(x_v)[0]


def bench_shape(n, c, h, w, dtype, residual, emit):
    shape = "%dx%dx%dx%d%s" % (n, c, h, w, "+res" if residual else "")
    itemsize = jnp.dtype(dtype).itemsize
    tensor_gb = n * c * h * w * itemsize / 1e9
    plan = fb._plan(n, c, h * w, itemsize, 0, residual)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.normal(size=(n, c, h, w)).astype(np.float32),
                    dtype=dtype)
    gamma = jnp.ones((c,), jnp.float32)
    beta = jnp.zeros((c,), jnp.float32)
    res = x * 0.5 if residual else None

    def row(which, ms, nbytes_gb):
        gbs = nbytes_gb / (ms / 1e3)
        emit({"shape": shape, "dtype": str(dtype), "which": which,
              "plan": None if plan is None else
              {"ch_axis": plan[0], "ab": list(plan[1]),
               "bwd_pallas": plan[2]},
              "ms": round(ms, 3), "gbs": round(gbs, 1),
              "pct_peak": round(100 * gbs / HBM_PEAK_GBS, 1)})

    # XLA baseline (always runs)
    ref = jax.jit(functools.partial(fb._gbn_ref, eps=1e-3, act="relu",
                                    group=16))
    ms = _time(ref, x, gamma, beta, res)
    row("xla_fwd", ms, tensor_gb * (3 if residual else 2) + tensor_gb)

    def loss(xx, rr):
        y, _, _ = fb._gbn_ref(xx, gamma, beta, rr, 1e-3, "relu", 16)
        return (y.astype(jnp.float32) ** 2).sum()
    gref = jax.jit(jax.grad(loss, argnums=(0, 1) if residual else (0,)))
    ms = _time(gref, x, res) if residual else _time(lambda a: gref(a, None),
                                                    x)
    row("xla_fwd_bwd", ms, tensor_gb * (8 if residual else 6))

    if plan is None:
        emit({"shape": shape, "which": "pallas", "plan": None,
              "note": "jnp fallback (no feasible VMEM plan)"})
        return
    ch_axis, ab, bwd_pallas = plan

    # pure-copy ceiling with the identical view/blocks/grid
    x_v = fb._to_view(x, ch_axis)
    cp = jax.jit(functools.partial(_call_copy, ab=ab, ch_axis=ch_axis))
    ms = _time(cp, x_v)
    row("copy", ms, 2 * tensor_gb)

    # fused fwd
    fwd = jax.jit(functools.partial(
        fb._call_fwd, eps=1e-3, act="relu", ab=ab, ch_axis=ch_axis))
    ms = _time(lambda a, r: fwd(a, gamma, beta, r), x_v,
               None if res is None else fb._to_view(res, ch_axis))
    row("fwd", ms, tensor_gb * (3 if residual else 2))

    if bwd_pallas:
        y_v, m, v = fwd(x_v, gamma, beta,
                        None if res is None else fb._to_view(res, ch_axis))
        gy_v = x_v * 0.1
        bwd = jax.jit(functools.partial(
            fb._call_bwd, eps=1e-3, act="relu", ab=ab, ch_axis=ch_axis))
        ms = _time(lambda: bwd(gy_v, x_v, y_v if residual else None,
                               gamma, beta, m, v))
        row("bwd", ms, tensor_gb * (5 if residual else 4))
    else:
        emit({"shape": shape, "which": "bwd", "note": "jnp hybrid bwd"})


def main():
    global SHAPES
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--out", default=None, help="also append JSON here")
    ap.add_argument("--residual", action="store_true",
                    help="bench the residual variants too")
    ap.add_argument("--self-test", action="store_true",
                    help="tiny shapes in interpret mode — validates the "
                         "plumbing without a chip (timings meaningless)")
    args = ap.parse_args()
    if args.self_test:
        SHAPES = [(8, 64, 6, 6), (8, 256, 6, 6)]
        # never touch the (shared) chip in self-test: pin the cpu
        # backend so _use_interpret() routes every kernel to interpret
        jax.config.update("jax_platforms", "cpu")
    sink = open(args.out, "a") if args.out else None

    def emit(obj):
        line = json.dumps(obj)
        print(line, flush=True)
        if sink:
            sink.write(line + "\n")
            sink.flush()

    backend = jax.default_backend()
    emit({"backend": backend, "note": "interpret mode (numbers are NOT "
          "kernel perf)" if backend != "tpu" else "on-chip"})
    dtype = jnp.dtype(args.dtype)
    for (n, c, h, w) in SHAPES:
        for residual in ([False, True] if args.residual else [False]):
            if residual and c < 128:
                continue
            try:
                bench_shape(n, c, h, w, dtype, residual, emit)
            except Exception as e:  # keep the sweep going; record why
                emit({"shape": "%dx%dx%dx%d" % (n, c, h, w),
                      "residual": residual, "error": repr(e)[:300]})
    if sink:
        sink.close()


if __name__ == "__main__":
    main()
