// Engine concurrency stress test — the gtest/sanitizer leg of SURVEY §5.2
// (reference: tests/cpp/engine/threaded_engine_test.cc + the USE_ASAN CI
// targets, CMakeLists.txt:59,356).
//
// Exercises the versioned-Var scheduler's correctness contract under load:
//   * writes serialize per var, reads run concurrently (final counter value
//     must equal the number of writers);
//   * dependency ordering: a writer chain onto one var is observed in
//     order by a reader pushed after it;
//   * sticky errors surface at WaitForVar;
//   * WaitForAll drains everything (no lost oprs, no deadlock at exit).
//
// Build/run (src/native/Makefile):
//   make engine-check          plain build + run
//   make asan-check            AddressSanitizer build + run
//   make tsan-check            ThreadSanitizer build + run
#include <sched.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
void* MXTEngineCreate(int num_workers);
void MXTEngineFree(void* h);
void* MXTEngineNewVar(void* h);
void MXTEngineDeleteVar(void* h, void* v);
int MXTEnginePushAsync(void* h, int (*fn)(void*), void* ctx,
                       void** const_vars, int n_const, void** mutable_vars,
                       int n_mutable, const char* name);
int MXTEngineWaitForVar(void* h, void* v, char* err_buf, int buf_len);
int MXTEngineWaitForAll(void* h, char* err_buf, int buf_len);
}

namespace {

struct Counter {
  long value = 0;            // guarded by the engine's per-var write grant
  std::atomic<int> readers{0};
  std::atomic<int> max_concurrent_readers{0};
};

int WriteOp(void* ctx) {
  Counter* c = static_cast<Counter*>(ctx);
  // not atomic on purpose: the engine must serialize writers per var
  long v = c->value;
  for (volatile int i = 0; i < 50; ++i) {
  }
  c->value = v + 1;
  return 0;
}

int ReadOp(void* ctx) {
  Counter* c = static_cast<Counter*>(ctx);
  int now = c->readers.fetch_add(1) + 1;
  int prev = c->max_concurrent_readers.load();
  while (now > prev &&
         !c->max_concurrent_readers.compare_exchange_weak(prev, now)) {
  }
  for (volatile int i = 0; i < 200; ++i) {
  }
  c->readers.fetch_sub(1);
  return 0;
}

// Rendezvous reader: holds its read grant until a SECOND reader arrives
// (bounded wait) — on a single-core host plain readers finish within one
// scheduling quantum, so overlap must be forced to be observable.  If the
// engine wrongly serialized readers this would time out and the
// max_concurrent_readers assertion fails.
int RendezvousReadOp(void* ctx) {
  Counter* c = static_cast<Counter*>(ctx);
  int now = c->readers.fetch_add(1) + 1;
  int prev = c->max_concurrent_readers.load();
  while (now > prev &&
         !c->max_concurrent_readers.compare_exchange_weak(prev, now)) {
  }
  for (long spins = 0; c->readers.load() < 2 && spins < 200000000L;
       ++spins) {
    if ((spins & 0xFFF) == 0) sched_yield();
  }
  c->readers.fetch_sub(1);
  return 0;
}

int FailOp(void*) { return 42; }

int failures = 0;

#define EXPECT(cond)                                          \
  do {                                                        \
    if (!(cond)) {                                            \
      std::fprintf(stderr, "FAILED: %s (line %d)\n", #cond,   \
                   __LINE__);                                 \
      ++failures;                                             \
    }                                                         \
  } while (0)

}  // namespace

int main() {
  char err[512];

  // ---- writers serialize, reads interleave -------------------------------
  {
    void* eng = MXTEngineCreate(4);
    void* var = MXTEngineNewVar(eng);
    Counter c;
    const int kWrites = 2000;
    for (int i = 0; i < kWrites; ++i) {
      EXPECT(MXTEnginePushAsync(eng, WriteOp, &c, nullptr, 0, &var, 1,
                                "w") == 0);
      if (i % 10 == 0) {
        EXPECT(MXTEnginePushAsync(eng, ReadOp, &c, &var, 1, nullptr, 0,
                                  "r") == 0);
      }
    }
    EXPECT(MXTEngineWaitForAll(eng, err, sizeof(err)) == 0);
    EXPECT(c.value == kWrites);
    MXTEngineDeleteVar(eng, var);
    MXTEngineFree(eng);
  }

  // ---- concurrent readers actually overlap -------------------------------
  {
    void* eng = MXTEngineCreate(4);
    void* var = MXTEngineNewVar(eng);
    Counter c;
    for (int i = 0; i < 4; ++i) {
      MXTEnginePushAsync(eng, RendezvousReadOp, &c, &var, 1, nullptr, 0,
                         "r");
    }
    MXTEngineWaitForAll(eng, err, sizeof(err));
    EXPECT(c.max_concurrent_readers.load() > 1);
    MXTEngineDeleteVar(eng, var);
    MXTEngineFree(eng);
  }

  // ---- sticky error surfaces at WaitForVar -------------------------------
  {
    void* eng = MXTEngineCreate(2);
    void* var = MXTEngineNewVar(eng);
    Counter c;
    MXTEnginePushAsync(eng, WriteOp, &c, nullptr, 0, &var, 1, "w");
    MXTEnginePushAsync(eng, FailOp, nullptr, nullptr, 0, &var, 1, "boom");
    err[0] = '\0';
    int rc = MXTEngineWaitForVar(eng, var, err, sizeof(err));
    EXPECT(rc != 0);
    EXPECT(std::strlen(err) > 0);
    MXTEngineDeleteVar(eng, var);
    MXTEngineFree(eng);
  }

  // ---- many vars, mixed graph, clean drain -------------------------------
  {
    void* eng = MXTEngineCreate(4);
    const int kVars = 64;
    std::vector<void*> vars(kVars);
    std::vector<Counter> cs(kVars);
    for (int i = 0; i < kVars; ++i) vars[i] = MXTEngineNewVar(eng);
    for (int round = 0; round < 200; ++round) {
      int a = round % kVars;
      int b = (round * 7 + 3) % kVars;
      if (a == b) b = (b + 1) % kVars;
      // read a, write b
      void* cv[1] = {vars[a]};
      void* mv[1] = {vars[b]};
      MXTEnginePushAsync(eng, WriteOp, &cs[b], cv, 1, mv, 1, "mix");
    }
    EXPECT(MXTEngineWaitForAll(eng, err, sizeof(err)) == 0);
    long total = 0;
    for (auto& c : cs) total += c.value;
    EXPECT(total == 200);
    for (int i = 0; i < kVars; ++i) MXTEngineDeleteVar(eng, vars[i]);
    MXTEngineFree(eng);
  }

  if (failures == 0) {
    std::printf("ENGINE_TEST_OK\n");
    return 0;
  }
  std::fprintf(stderr, "%d failures\n", failures);
  return 1;
}
