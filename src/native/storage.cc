// Host storage managers: pooled aligned allocator + POSIX shm.
//
// Reference: src/storage/pooled_storage_manager.h:52 (size-bucketed pool
// with round-up), src/storage/cpu_shared_storage_manager.h (shm segments
// for DataLoader worker IPC).  Device memory is XLA's; these cover the
// HOST side: staging buffers for input pipelines and shared-memory
// transport between data-loading processes.
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace mxtpu {

static constexpr size_t kAlign = 64;

static size_t RoundSize(size_t size) {
  // round to the next power of two ≥ 4096 (pooled_storage_manager.h
  // GPUPooledRoundedStorageManager semantics, host-adapted)
  size_t r = 4096;
  while (r < size) r <<= 1;
  return r;
}

class PooledStorage {
 public:
  void* Alloc(size_t size) {
    size_t bucket = RoundSize(size);
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pool_.find(bucket);
      if (it != pool_.end() && !it->second.empty()) {
        void* p = it->second.back();
        it->second.pop_back();
        pooled_bytes_ -= bucket;
        return p;
      }
    }
    void* p = nullptr;
    if (posix_memalign(&p, kAlign, bucket) != 0) return nullptr;
    return p;
  }

  void Free(void* ptr, size_t size) {
    size_t bucket = RoundSize(size);
    std::lock_guard<std::mutex> lk(mu_);
    pool_[bucket].push_back(ptr);
    pooled_bytes_ += bucket;
  }

  void EmptyCache() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : pool_)
      for (void* p : kv.second) free(p);
    pool_.clear();
    pooled_bytes_ = 0;
  }

  size_t PooledBytes() {
    std::lock_guard<std::mutex> lk(mu_);
    return pooled_bytes_;
  }

 private:
  std::mutex mu_;
  std::unordered_map<size_t, std::vector<void*>> pool_;
  size_t pooled_bytes_ = 0;
};

static PooledStorage* GlobalPool() {
  static PooledStorage pool;
  return &pool;
}

}  // namespace mxtpu

extern "C" {

void* MXTStorageAlloc(size_t size) {
  return mxtpu::GlobalPool()->Alloc(size);
}

void MXTStorageFree(void* ptr, size_t size) {
  mxtpu::GlobalPool()->Free(ptr, size);
}

void MXTStorageEmptyCache() { mxtpu::GlobalPool()->EmptyCache(); }

size_t MXTStoragePooledBytes() { return mxtpu::GlobalPool()->PooledBytes(); }

// ---- POSIX shared memory (cpu_shared_storage_manager.h analog) ----------

void* MXTShmCreate(const char* name, size_t size) {
  int fd = shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* p = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return p == MAP_FAILED ? nullptr : p;
}

void* MXTShmAttach(const char* name, size_t size) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  void* p = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return p == MAP_FAILED ? nullptr : p;
}

int MXTShmDetach(void* ptr, size_t size) { return munmap(ptr, size); }

int MXTShmUnlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
