// Host-side dependency engine: versioned-Var async scheduler.
//
// Reference semantics: include/mxnet/engine.h:117 (Engine API),
// src/engine/threaded_engine.h:71-574 (ThreadedVar read/write queues,
// exception capture per var, WaitForVar/WaitForAll).
//
// TPU-native role: XLA handles device-side async; this engine schedules
// HOST work — IO pipelines, checkpoint writes, record decoding — with the
// same read/write-var dependency discipline, so Python-level pipelines
// keep the reference's ordering guarantees (writes serialize per var,
// reads run concurrently, errors surface at WaitForVar).
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace mxtpu {

typedef int (*OprFn)(void*);  // user callback: 0 = ok, nonzero = error

struct Opr;

struct VarQueueEntry {
  Opr* opr;
  bool is_write;
};

struct Var {
  std::mutex mu;
  std::deque<VarQueueEntry> queue;  // FIFO of not-yet-granted accesses
  int running_reads = 0;
  bool writer_running = false;
  bool has_error = false;
  std::string error;
};

struct Opr {
  OprFn fn;
  void* ctx;
  std::vector<Var*> const_vars;
  std::vector<Var*> mutable_vars;
  std::atomic<int> pending{0};
  std::string name;
};

class Engine {
 public:
  explicit Engine(int num_workers) : shutdown_(false) {
    if (num_workers < 1) num_workers = 1;
    for (int i = 0; i < num_workers; ++i) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~Engine() {
    WaitForAll(nullptr);
    {
      std::lock_guard<std::mutex> lk(task_mu_);
      shutdown_ = true;
    }
    task_cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  Var* NewVar() { return new Var(); }

  void DeleteVar(Var* v) { delete v; }  // caller ensures quiescence

  void Push(OprFn fn, void* ctx, Var** cvars, int nc, Var** mvars, int nm,
            const char* name) {
    Opr* op = new Opr();
    op->fn = fn;
    op->ctx = ctx;
    op->name = name ? name : "";
    op->const_vars.assign(cvars, cvars + nc);
    op->mutable_vars.assign(mvars, mvars + nm);
    outstanding_.fetch_add(1);
    // +1 sentinel so the op can't dispatch while we are still appending
    op->pending.store(nc + nm + 1);
    for (Var* v : op->const_vars) AppendRead(v, op);
    for (Var* v : op->mutable_vars) AppendWrite(v, op);
    DecPending(op);  // drop sentinel
  }

  long Outstanding() const { return outstanding_.load(); }

  // Block until every queued op before this call has finished.
  int WaitForAll(std::string* err) {
    std::unique_lock<std::mutex> lk(wait_mu_);
    wait_cv_.wait(lk, [this] { return outstanding_.load() == 0; });
    std::lock_guard<std::mutex> el(err_mu_);
    if (!first_error_.empty()) {
      if (err) *err = first_error_;
      first_error_.clear();  // reported once, like MXNet's on-wait rethrow
      return -1;
    }
    return 0;
  }

  // Block until all current writers/readers of var complete; rethrow the
  // var's sticky error like WaitToRead (threaded_engine.h:495).
  int WaitForVar(Var* var, std::string* err) {
    struct WaitCtx {
      std::mutex mu;
      std::condition_variable cv;
      bool done = false;
    } wc;
    auto fn = [](void* p) -> int {
      WaitCtx* w = static_cast<WaitCtx*>(p);
      std::lock_guard<std::mutex> lk(w->mu);
      w->done = true;
      w->cv.notify_all();
      return 0;
    };
    Var* cv[1] = {var};
    Push(fn, &wc, cv, 1, nullptr, 0, "__wait__");
    std::unique_lock<std::mutex> lk(wc.mu);
    wc.cv.wait(lk, [&wc] { return wc.done; });
    std::lock_guard<std::mutex> vl(var->mu);
    if (var->has_error) {
      if (err) *err = var->error;
      return -1;
    }
    return 0;
  }

 private:
  void AppendRead(Var* v, Opr* op) {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->queue.empty() && !v->writer_running) {
      ++v->running_reads;
      DecPending(op);
    } else {
      v->queue.push_back({op, false});
    }
  }

  void AppendWrite(Var* v, Opr* op) {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->queue.empty() && !v->writer_running && v->running_reads == 0) {
      v->writer_running = true;
      DecPending(op);
    } else {
      v->queue.push_back({op, true});
    }
  }

  void DecPending(Opr* op) {
    if (op->pending.fetch_sub(1) == 1) {
      {
        std::lock_guard<std::mutex> lk(task_mu_);
        ready_.push(op);
      }
      task_cv_.notify_one();
    }
  }

  void CompleteVarAccess(Var* v, bool was_write, bool op_failed,
                         const std::string& msg,
                         std::vector<Opr*>* newly_ready) {
    std::lock_guard<std::mutex> lk(v->mu);
    if (was_write) {
      v->writer_running = false;
      if (op_failed) {
        v->has_error = true;
        v->error = msg;
      } else {
        v->has_error = false;  // successful write clears the sticky error
        v->error.clear();
      }
    } else {
      --v->running_reads;
    }
    // grant from queue head, preserving FIFO: a run of reads, or one write
    while (!v->queue.empty()) {
      VarQueueEntry& e = v->queue.front();
      if (e.is_write) {
        if (v->running_reads == 0 && !v->writer_running) {
          v->writer_running = true;
          Opr* op = e.opr;
          v->queue.pop_front();
          if (op->pending.fetch_sub(1) == 1) newly_ready->push_back(op);
        }
        break;
      }
      if (v->writer_running) break;
      ++v->running_reads;
      Opr* op = e.opr;
      v->queue.pop_front();
      if (op->pending.fetch_sub(1) == 1) newly_ready->push_back(op);
    }
  }

  void WorkerLoop() {
    for (;;) {
      Opr* op = nullptr;
      {
        std::unique_lock<std::mutex> lk(task_mu_);
        task_cv_.wait(lk, [this] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        op = ready_.front();
        ready_.pop();
      }
      int rc = 0;
      std::string msg;
      rc = op->fn(op->ctx);
      if (rc != 0) {
        msg = "operation '" + op->name + "' failed with code " +
              std::to_string(rc);
        std::lock_guard<std::mutex> el(err_mu_);
        if (first_error_.empty()) first_error_ = msg;
      }
      std::vector<Opr*> newly_ready;
      for (Var* v : op->const_vars)
        CompleteVarAccess(v, false, false, msg, &newly_ready);
      for (Var* v : op->mutable_vars)
        CompleteVarAccess(v, true, rc != 0, msg, &newly_ready);
      delete op;
      if (!newly_ready.empty()) {
        {
          std::lock_guard<std::mutex> lk(task_mu_);
          for (Opr* r : newly_ready) ready_.push(r);
        }
        task_cv_.notify_all();
      }
      if (outstanding_.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk(wait_mu_);
        wait_cv_.notify_all();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::mutex task_mu_;
  std::condition_variable task_cv_;
  std::queue<Opr*> ready_;
  bool shutdown_;

  std::atomic<long> outstanding_{0};
  std::mutex wait_mu_;
  std::condition_variable wait_cv_;

  std::mutex err_mu_;
  std::string first_error_;
};

}  // namespace mxtpu

// ---------------------------------------------------------------------------
// flat C ABI (the include/mxnet/c_api.h MXEngine* analog)
// ---------------------------------------------------------------------------

extern "C" {

void* MXTEngineCreate(int num_workers) {
  return new mxtpu::Engine(num_workers);
}

void MXTEngineFree(void* h) { delete static_cast<mxtpu::Engine*>(h); }

void* MXTEngineNewVar(void* h) {
  return static_cast<mxtpu::Engine*>(h)->NewVar();
}

void MXTEngineDeleteVar(void* h, void* v) {
  static_cast<mxtpu::Engine*>(h)->DeleteVar(static_cast<mxtpu::Var*>(v));
}

int MXTEnginePushAsync(void* h, int (*fn)(void*), void* ctx,
                       void** const_vars, int n_const, void** mutable_vars,
                       int n_mutable, const char* name) {
  static_cast<mxtpu::Engine*>(h)->Push(
      fn, ctx, reinterpret_cast<mxtpu::Var**>(const_vars), n_const,
      reinterpret_cast<mxtpu::Var**>(mutable_vars), n_mutable, name);
  return 0;
}

int MXTEngineWaitForVar(void* h, void* v, char* err_buf, int buf_len) {
  std::string err;
  int rc = static_cast<mxtpu::Engine*>(h)->WaitForVar(
      static_cast<mxtpu::Var*>(v), &err);
  if (rc != 0 && err_buf && buf_len > 0) {
    std::strncpy(err_buf, err.c_str(), buf_len - 1);
    err_buf[buf_len - 1] = '\0';
  }
  return rc;
}

long MXTEngineOutstanding(void* h) {
  return static_cast<mxtpu::Engine*>(h)->Outstanding();
}

int MXTEngineWaitForAll(void* h, char* err_buf, int buf_len) {
  std::string err;
  int rc = static_cast<mxtpu::Engine*>(h)->WaitForAll(&err);
  if (rc != 0 && err_buf && buf_len > 0) {
    std::strncpy(err_buf, err.c_str(), buf_len - 1);
    err_buf[buf_len - 1] = '\0';
  }
  return rc;
}

}  // extern "C"
