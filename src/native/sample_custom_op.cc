// Sample custom-op library (lib_api.h / example/extensions/lib_custom_op
// analog) for the MXTPULibOp* contract consumed by
// incubator_mxnet_tpu/library.py.
//
// Build: make libsample_custom_op.so   (src/native/Makefile)
#include <cmath>
#include <cstdint>
#include <cstring>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

MXTPU_API const char* MXTPULibOpList() {
  return "[{\"name\": \"my_gelu\", \"num_inputs\": 1},"
         " {\"name\": \"my_weighted_add\", \"num_inputs\": 2}]";
}

static int64_t NumElems(const int64_t* shape, int ndim) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

MXTPU_API int MXTPULibOpCompute(const char* name, int n_in,
                                const float** ins, const int64_t* shape,
                                int ndim, float* out) {
  const int64_t n = NumElems(shape, ndim);
  if (std::strcmp(name, "my_gelu") == 0 && n_in == 1) {
    const float* x = ins[0];
    for (int64_t i = 0; i < n; ++i) {
      const float v = x[i];
      out[i] = 0.5f * v * (1.0f + std::tanh(0.7978845608f *
                                            (v + 0.044715f * v * v * v)));
    }
    return 0;
  }
  if (std::strcmp(name, "my_weighted_add") == 0 && n_in == 2) {
    for (int64_t i = 0; i < n; ++i) {
      out[i] = 0.75f * ins[0][i] + 0.25f * ins[1][i];
    }
    return 0;
  }
  return 1;  // unknown op / arity
}
