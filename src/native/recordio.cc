// RecordIO reader/writer — dmlc-core recordio format.
//
// Reference: dmlc-core recordio (used via src/io/, python recordio.py):
//   [kMagic:u32][lrec:u32][data...][pad to 4B]
// lrec = (cflag << 29) | length.  Payloads embedding the magic are split
// into multi-part records (cflag 1=first, 2=middle, 3=last) with the
// magic removed at split points and re-inserted on read — identical to
// dmlc-core and to incubator_mxnet_tpu/recordio.py, so files are
// byte-interchangeable between the native and pure-Python paths and with
// reference-written .rec files.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace mxtpu {

static constexpr uint32_t kMagic = 0xced7230a;

struct Writer {
  FILE* f;
};

struct Reader {
  FILE* f;
  std::vector<char> buf;
};

}  // namespace mxtpu

extern "C" {

void* MXTRecordIOWriterCreate(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  return new mxtpu::Writer{f};
}

long MXTRecordIOWriterTell(void* h) {
  return ftell(static_cast<mxtpu::Writer*>(h)->f);
}

static int WritePart(FILE* f, const char* data, size_t len,
                     uint32_t cflag) {
  if (len >= (1u << 29)) return -2;
  uint32_t magic = mxtpu::kMagic;
  uint32_t lrec = (cflag << 29) | static_cast<uint32_t>(len);
  if (fwrite(&magic, 4, 1, f) != 1) return -1;
  if (fwrite(&lrec, 4, 1, f) != 1) return -1;
  if (len && fwrite(data, 1, len, f) != len) return -1;
  size_t pad = (4 - (len & 3)) & 3;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, f) != pad) return -1;
  return 0;
}

int MXTRecordIOWriterWrite(void* h, const char* data, size_t len) {
  FILE* f = static_cast<mxtpu::Writer*>(h)->f;
  // split the payload at embedded magic words (dmlc recordio.cc)
  uint32_t magic = mxtpu::kMagic;
  std::vector<std::pair<size_t, size_t>> parts;  // (offset, len)
  size_t start = 0;
  for (size_t i = 0; len >= 4 && i + 4 <= len; ++i) {
    uint32_t w;
    std::memcpy(&w, data + i, 4);
    if (w == magic) {
      parts.emplace_back(start, i - start);
      start = i + 4;
      i += 3;
    }
  }
  parts.emplace_back(start, len - start);
  if (parts.size() == 1)
    return WritePart(f, data + parts[0].first, parts[0].second, 0);
  for (size_t k = 0; k < parts.size(); ++k) {
    uint32_t cflag = (k == 0) ? 1 : (k + 1 == parts.size() ? 3 : 2);
    int rc = WritePart(f, data + parts[k].first, parts[k].second, cflag);
    if (rc != 0) return rc;
  }
  return 0;
}

void MXTRecordIOWriterFree(void* h) {
  mxtpu::Writer* w = static_cast<mxtpu::Writer*>(h);
  fclose(w->f);
  delete w;
}

void* MXTRecordIOReaderCreate(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  return new mxtpu::Reader{f, {}};
}

int MXTRecordIOReaderSeek(void* h, long pos) {
  return fseek(static_cast<mxtpu::Reader*>(h)->f, pos, SEEK_SET);
}

long MXTRecordIOReaderTell(void* h) {
  return ftell(static_cast<mxtpu::Reader*>(h)->f);
}

// Returns 1 and sets (*out, *out_len) on success, 0 on clean EOF,
// negative on corruption.  The buffer stays valid until the next read.
int MXTRecordIOReaderRead(void* h, const char** out, size_t* out_len) {
  mxtpu::Reader* r = static_cast<mxtpu::Reader*>(h);
  r->buf.clear();
  bool expect_more = false;
  for (;;) {
    uint32_t magic = 0;
    size_t n = fread(&magic, 1, 4, r->f);
    if (n == 0) return expect_more ? -2 : 0;  // EOF (truncated if mid-rec)
    if (n != 4 || magic != mxtpu::kMagic) return -1;
    uint32_t lrec = 0;
    if (fread(&lrec, 1, 4, r->f) != 4) return -1;
    uint32_t cflag = lrec >> 29;
    size_t len = lrec & ((1u << 29) - 1);
    size_t off = r->buf.size();
    if (cflag == 2 || cflag == 3) {
      // re-insert the magic removed at the split point
      uint32_t m = mxtpu::kMagic;
      r->buf.resize(off + 4);
      std::memcpy(r->buf.data() + off, &m, 4);
      off += 4;
    }
    r->buf.resize(off + len);
    if (len && fread(r->buf.data() + off, 1, len, r->f) != len) return -1;
    size_t pad = (4 - (len & 3)) & 3;
    if (pad) fseek(r->f, static_cast<long>(pad), SEEK_CUR);
    if (cflag == 0 || cflag == 3) {
      *out = r->buf.data();
      *out_len = r->buf.size();
      return 1;
    }
    expect_more = true;
  }
}

void MXTRecordIOReaderFree(void* h) {
  mxtpu::Reader* rd = static_cast<mxtpu::Reader*>(h);
  fclose(rd->f);
  delete rd;
}

}  // extern "C"
