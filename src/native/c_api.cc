// MXNet-compatible C ABI over the TPU-native runtime.
//
// Reference contract: include/mxnet/c_api.h (242 MXNET_DLL functions) and
// include/mxnet/c_predict_api.h:84-289 (serving ABI).  In the reference the
// C layer sits UNDER the Python frontend; here the compute runtime IS
// Python/JAX, so the C ABI is a native shim that drives the runtime through
// the embedded CPython API (incubator_mxnet_tpu.capi_impl does the
// marshalling).  Handles are strong PyObject references; every entry point
// takes the GIL, so the library is callable from any C/C++ thread — the
// same contract the reference's thread-safe predict API documents.
//
// Implemented surface (the subset every binding/serving path needs):
//   error     MXGetLastError, MXGetVersion
//   ndarray   MXNDArrayCreate/Ex, Free, SyncCopyFromCPU, SyncCopyToCPU,
//             GetShape, GetDType, WaitToRead, MXNDArraySave, MXNDArrayLoad
//   ops       MXListAllOpNames, MXImperativeInvokeByName
//   symbol    MXSymbolCreateFromJSON, SaveToJSON, Free, ListArguments,
//             ListOutputs, ListAuxiliaryStates
//   predict   MXPredCreate, SetInput, Forward, GetOutputShape, GetOutput,
//             Free
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* PredictorHandle;

namespace {

thread_local std::string g_last_error;
// per-thread scratch keeping returned pointers alive until the next call
// (the reference uses MXAPIThreadLocalEntry the same way)
thread_local std::vector<uint32_t> g_shape_buf;
thread_local std::vector<std::string> g_str_store;
thread_local std::vector<const char*> g_ptr_store;
thread_local std::string g_json_buf;
thread_local std::vector<NDArrayHandle> g_handle_store;

int Fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

int FailFromPython() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      msg = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return Fail(msg);
}

// Bring up the interpreter once for standalone C/C++ consumers (no-op when
// the host process is already a live interpreter).  Must run BEFORE any
// PyGILState_Ensure: taking the GIL on an uninitialized runtime crashes.
void EnsureInterpreter() {
  static std::once_flag once;
  std::call_once(once, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // drop the GIL the init acquired so PyGILState_* manages it from
      // any caller thread
      PyEval_SaveThread();
    }
  });
}

class Gil {
 public:
  Gil() {
    EnsureInterpreter();
    state_ = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// Import the marshalling module (caller must hold the GIL).  No call_once:
// blocking in a foreign once while holding the GIL would deadlock against
// the importing thread (imports release the GIL mid-way); CPython's
// sys.modules makes repeat imports cheap and idempotent, and the import
// lock serializes racing first-imports correctly under the GIL.
PyObject* Impl() {
  PyObject* impl = PyImport_ImportModule("incubator_mxnet_tpu.capi_impl");
  if (impl == nullptr) PyErr_Print();
  return impl;
}

PyObject* CallImpl(const char* fn, PyObject* args) {
  PyObject* mod = Impl();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

int StoreStringList(PyObject* list, uint32_t* out_size,
                    const char*** out_array) {
  g_str_store.clear();
  g_ptr_store.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_str_store.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(list, i)));
  }
  for (auto& s : g_str_store) g_ptr_store.push_back(s.c_str());
  *out_size = static_cast<uint32_t>(n);
  *out_array = g_ptr_store.data();
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// error / version
// ---------------------------------------------------------------------------

MXTPU_API const char* MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXGetVersion(int* out) {
  *out = 10600;  // reports 1.6.0-compatible surface
  return 0;
}

// ---------------------------------------------------------------------------
// NDArray
// ---------------------------------------------------------------------------

MXTPU_API int MXNDArrayCreateEx(const uint32_t* shape, uint32_t ndim,
                                int dev_type, int dev_id, int delay_alloc,
                                int dtype, NDArrayHandle* out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i) {
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* args = Py_BuildValue("(Ni)", shp, dtype);
  PyObject* res = CallImpl("ndarray_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;  // strong reference transferred to the handle
  return 0;
}

MXTPU_API int MXNDArrayCreate(const uint32_t* shape, uint32_t ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              NDArrayHandle* out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

MXTPU_API int MXNDArrayFree(NDArrayHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                                       size_t size) {
  Gil gil;
  // size is an element count in the reference ABI; bytes = count * itemsize
  PyObject* dt = PyObject_GetAttrString(static_cast<PyObject*>(handle),
                                        "dtype");
  if (dt == nullptr) return FailFromPython();
  PyObject* isz = PyObject_GetAttrString(dt, "itemsize");
  Py_DECREF(dt);
  if (isz == nullptr) return FailFromPython();
  size_t nbytes = size * PyLong_AsSize_t(isz);
  Py_DECREF(isz);
  PyObject* bytes = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 bytes);
  PyObject* res = CallImpl("ndarray_sync_copy_from", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                                     size_t size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_to_bytes", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  char* buf = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(res, &buf, &n);
  PyObject* dt = PyObject_GetAttrString(static_cast<PyObject*>(handle),
                                        "dtype");
  PyObject* isz = dt ? PyObject_GetAttrString(dt, "itemsize") : nullptr;
  size_t want = size * (isz ? PyLong_AsSize_t(isz) : 1);
  Py_XDECREF(dt);
  Py_XDECREF(isz);
  std::memcpy(data, buf, want < static_cast<size_t>(n) ? want
                                                       : static_cast<size_t>(n));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayWaitToRead(NDArrayHandle handle) {
  Gil gil;
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "wait_to_read", nullptr);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayGetShape(NDArrayHandle handle, uint32_t* out_dim,
                                const uint32_t** out_pdata) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_shape_buf[i] =
        static_cast<uint32_t>(PyLong_AsLong(PyList_GetItem(res, i)));
  }
  Py_DECREF(res);
  *out_dim = static_cast<uint32_t>(n);
  *out_pdata = g_shape_buf.data();
  return 0;
}

MXTPU_API int MXNDArrayGetDType(NDArrayHandle handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_dtype", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArraySave(const char* fname, uint32_t num_args,
                            NDArrayHandle* args_, const char** keys) {
  Gil gil;
  PyObject* handles = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    Py_INCREF(static_cast<PyObject*>(args_[i]));
    PyList_SetItem(handles, i, static_cast<PyObject*>(args_[i]));
  }
  PyObject* names;
  if (keys != nullptr) {
    names = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
    }
  } else {
    names = PyList_New(0);
  }
  PyObject* args = Py_BuildValue("(sNN)", fname, handles, names);
  PyObject* res = CallImpl("ndarray_save", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayLoad(const char* fname, uint32_t* out_size,
                            NDArrayHandle** out_arr, uint32_t* out_name_size,
                            const char*** out_names) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* res = CallImpl("ndarray_load", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  PyObject* arrs = PyTuple_GetItem(res, 0);
  PyObject* names = PyTuple_GetItem(res, 1);
  Py_ssize_t n = PyList_Size(arrs);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(arrs, i);
    Py_INCREF(item);
    g_handle_store.push_back(item);
  }
  *out_size = static_cast<uint32_t>(n);
  *out_arr = g_handle_store.data();
  StoreStringList(names, out_name_size, out_names);
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// ops
// ---------------------------------------------------------------------------

MXTPU_API int MXListAllOpNames(uint32_t* out_size, const char*** out_array) {
  Gil gil;
  PyObject* res = CallImpl("list_op_names", nullptr);
  if (res == nullptr) return FailFromPython();
  StoreStringList(res, out_size, out_array);
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXImperativeInvokeByName(
    const char* op_name, int num_inputs, NDArrayHandle* inputs,
    int* num_outputs, NDArrayHandle** outputs, int num_params,
    const char** param_keys, const char** param_vals) {
  Gil gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    Py_INCREF(static_cast<PyObject*>(inputs[i]));
    PyList_SetItem(ins, i, static_cast<PyObject*>(inputs[i]));
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  // caller-provided outputs = in-place write request (MXImperativeInvokeEx
  // contract, src/c_api/c_api_ndarray.cc:138)
  const bool provided = *outputs != nullptr && *num_outputs > 0;
  PyObject* pouts;
  if (provided) {
    pouts = PyList_New(*num_outputs);
    for (int i = 0; i < *num_outputs; ++i) {
      PyObject* o = static_cast<PyObject*>((*outputs)[i]);
      Py_INCREF(o);
      PyList_SetItem(pouts, i, o);
    }
  } else {
    pouts = Py_None;
    Py_INCREF(pouts);
  }
  PyObject* args = Py_BuildValue("(sNNNN)", op_name, ins, keys, vals, pouts);
  PyObject* res = CallImpl("imperative_invoke", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  if (provided) {  // results landed in the caller's handles
    Py_DECREF(res);
    return 0;
  }
  Py_ssize_t n = PyList_Size(res);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(res, i);
    Py_INCREF(item);
    g_handle_store.push_back(item);
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = g_handle_store.data();
  return 0;
}

// ---------------------------------------------------------------------------
// Symbol
// ---------------------------------------------------------------------------

MXTPU_API int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* res = CallImpl("symbol_from_json", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_to_json", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_json = g_json_buf.c_str();
  return 0;
}

MXTPU_API int MXSymbolFree(SymbolHandle sym) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(sym));
  return 0;
}

static int SymbolStrList(const char* fn, SymbolHandle sym, uint32_t* out_size,
                         const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl(fn, args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  StoreStringList(res, out_size, out_array);
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXSymbolListArguments(SymbolHandle sym, uint32_t* out_size,
                                    const char*** out_array) {
  return SymbolStrList("symbol_list_arguments", sym, out_size, out_array);
}

MXTPU_API int MXSymbolListOutputs(SymbolHandle sym, uint32_t* out_size,
                                  const char*** out_array) {
  return SymbolStrList("symbol_list_outputs", sym, out_size, out_array);
}

MXTPU_API int MXSymbolListAuxiliaryStates(SymbolHandle sym,
                                          uint32_t* out_size,
                                          const char*** out_array) {
  return SymbolStrList("symbol_list_aux", sym, out_size, out_array);
}

// Atomic-symbol creator reflection (MXSymbolListAtomicSymbolCreators +
// MXSymbolGetAtomicSymbolInfo, src/c_api/c_api_symbolic.cc) — the surface
// the reference code-gens every language binding's op wrappers from.
// Creator handles are interned op-name strings.
typedef void* AtomicSymbolCreator;

namespace {
thread_local std::vector<std::string> g_creator_names;
thread_local std::vector<AtomicSymbolCreator> g_creator_ptrs;
thread_local std::string g_info_name, g_info_desc;
thread_local std::vector<std::string> g_info_store[3];
thread_local std::vector<const char*> g_info_ptrs[3];
}  // namespace

MXTPU_API int MXSymbolListAtomicSymbolCreators(uint32_t* out_size,
                                               AtomicSymbolCreator** out) {
  Gil gil;
  PyObject* res = CallImpl("list_op_names", nullptr);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_creator_names.clear();
  g_creator_ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_creator_names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(res, i)));
  }
  Py_DECREF(res);
  for (auto& s : g_creator_names) {
    g_creator_ptrs.push_back(const_cast<char*>(s.c_str()));
  }
  *out_size = static_cast<uint32_t>(n);
  *out = g_creator_ptrs.data();
  return 0;
}

MXTPU_API int MXSymbolGetAtomicSymbolInfo(
    AtomicSymbolCreator creator, const char** name, const char** description,
    uint32_t* num_args, const char*** arg_names, const char*** arg_types,
    const char*** arg_descriptions) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", static_cast<const char*>(creator));
  PyObject* res = CallImpl("op_info_strings", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_info_name = PyUnicode_AsUTF8(PyTuple_GetItem(res, 0));
  g_info_desc = PyUnicode_AsUTF8(PyTuple_GetItem(res, 1));
  const char*** outs[3] = {arg_names, arg_types, arg_descriptions};
  uint32_t n = 0;
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GetItem(res, 2 + g);
    Py_ssize_t m = PyList_Size(lst);
    g_info_store[g].clear();
    g_info_ptrs[g].clear();
    for (Py_ssize_t i = 0; i < m; ++i) {
      g_info_store[g].emplace_back(PyUnicode_AsUTF8(PyList_GetItem(lst, i)));
    }
    for (auto& s : g_info_store[g]) g_info_ptrs[g].push_back(s.c_str());
    *outs[g] = g_info_ptrs[g].data();
    n = static_cast<uint32_t>(m);
  }
  Py_DECREF(res);
  *name = g_info_name.c_str();
  *description = g_info_desc.c_str();
  *num_args = n;
  return 0;
}

MXTPU_API int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  PyObject* res = CallImpl("symbol_create_variable", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

// One-shot CreateAtomicSymbol + Compose (src/c_api/c_api_symbolic.cc):
// builds the op node over named/positional input symbols.  input_keys may be
// nullptr (all positional) and individual entries may be nullptr.
MXTPU_API int MXSymbolCreateFromOp(const char* op_name, uint32_t num_params,
                                   const char** param_keys,
                                   const char** param_vals,
                                   uint32_t num_inputs,
                                   const char** input_keys,
                                   SymbolHandle* inputs, const char* name,
                                   SymbolHandle* out) {
  Gil gil;
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (uint32_t i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* in_names = PyList_New(num_inputs);
  PyObject* in_syms = PyList_New(num_inputs);
  for (uint32_t i = 0; i < num_inputs; ++i) {
    const char* k = input_keys != nullptr ? input_keys[i] : nullptr;
    PyList_SetItem(in_names, i,
                   k != nullptr ? PyUnicode_FromString(k)
                                : (Py_INCREF(Py_None), Py_None));
    PyObject* s = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(s);
    PyList_SetItem(in_syms, i, s);
  }
  PyObject* args = Py_BuildValue("(sNNNNs)", op_name, keys, vals, in_names,
                                 in_syms, name != nullptr ? name : "");
  PyObject* res = CallImpl("symbol_create_from_op", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

namespace {

// arena for MXSymbolInferShape outputs (alive until the next call on this
// thread, mirroring MXAPIThreadLocalEntry)
thread_local std::vector<std::vector<uint32_t>> g_is_shapes[3];
thread_local std::vector<uint32_t> g_is_ndim[3];
thread_local std::vector<const uint32_t*> g_is_ptr[3];

int StoreShapeGroup(PyObject* lst, int slot, uint32_t* out_size,
                    const uint32_t** out_ndim, const uint32_t*** out_data) {
  auto& shapes = g_is_shapes[slot];
  auto& ndims = g_is_ndim[slot];
  auto& ptrs = g_is_ptr[slot];
  shapes.clear();
  ndims.clear();
  ptrs.clear();
  Py_ssize_t n = PyList_Size(lst);
  shapes.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* shp = PyList_GetItem(lst, i);
    Py_ssize_t nd = PyList_Size(shp);
    for (Py_ssize_t d = 0; d < nd; ++d) {
      shapes[i].push_back(static_cast<uint32_t>(
          PyLong_AsLong(PyList_GetItem(shp, d))));
    }
    ndims.push_back(static_cast<uint32_t>(nd));
  }
  for (auto& s : shapes) ptrs.push_back(s.data());
  *out_size = static_cast<uint32_t>(n);
  *out_ndim = ndims.data();
  *out_data = ptrs.data();
  return 0;
}

int InferShapeImpl(SymbolHandle sym, uint32_t num_args, const char** keys,
                   const uint32_t* arg_ind_ptr,
                   const uint32_t* arg_shape_data, uint32_t* in_size,
                   const uint32_t** in_ndim, const uint32_t*** in_data,
                   uint32_t* out_size, const uint32_t** out_ndim,
                   const uint32_t*** out_data, uint32_t* aux_size,
                   const uint32_t** aux_ndim, const uint32_t*** aux_data,
                   int* complete, int partial) {
  Gil gil;
  PyObject* pkeys = PyList_New(num_args);
  PyObject* pshapes = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(keys[i]));
    uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (uint32_t d = lo; d < hi; ++d) {
      PyList_SetItem(shp, d - lo, PyLong_FromLong(arg_shape_data[d]));
    }
    PyList_SetItem(pshapes, i, shp);
  }
  PyObject* args = Py_BuildValue("(ONNi)", static_cast<PyObject*>(sym),
                                 pkeys, pshapes, partial);
  PyObject* res = CallImpl("symbol_infer_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  StoreShapeGroup(PyTuple_GetItem(res, 0), 0, in_size, in_ndim, in_data);
  StoreShapeGroup(PyTuple_GetItem(res, 1), 1, out_size, out_ndim, out_data);
  StoreShapeGroup(PyTuple_GetItem(res, 2), 2, aux_size, aux_ndim, aux_data);
  *complete = PyObject_IsTrue(PyTuple_GetItem(res, 3));
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXSymbolInferShape(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete) {
  return InferShapeImpl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                        in_shape_size, in_shape_ndim, in_shape_data,
                        out_shape_size, out_shape_ndim, out_shape_data,
                        aux_shape_size, aux_shape_ndim, aux_shape_data,
                        complete, 0);
}

MXTPU_API int MXSymbolInferShapePartial(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete) {
  return InferShapeImpl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                        in_shape_size, in_shape_ndim, in_shape_data,
                        out_shape_size, out_shape_ndim, out_shape_data,
                        aux_shape_size, aux_shape_ndim, aux_shape_data,
                        complete, 1);
}

// ---------------------------------------------------------------------------
// Executor (MXExecutorBind family, include/mxnet/c_api.h)
// ---------------------------------------------------------------------------

typedef void* ExecutorHandle;

MXTPU_API int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                             uint32_t len, NDArrayHandle* in_args,
                             NDArrayHandle* arg_grad_store,
                             uint32_t* grad_req_type, uint32_t aux_len,
                             NDArrayHandle* aux_states, ExecutorHandle* out) {
  (void)dev_type;
  (void)dev_id;
  Gil gil;
  PyObject* pargs = PyList_New(len);
  PyObject* pgrads = PyList_New(len);
  PyObject* preqs = PyList_New(len);
  for (uint32_t i = 0; i < len; ++i) {
    PyObject* a = static_cast<PyObject*>(in_args[i]);
    Py_INCREF(a);
    PyList_SetItem(pargs, i, a);
    PyObject* g = arg_grad_store != nullptr && arg_grad_store[i] != nullptr
                      ? static_cast<PyObject*>(arg_grad_store[i])
                      : Py_None;
    Py_INCREF(g);
    PyList_SetItem(pgrads, i, g);
    PyList_SetItem(preqs, i,
                   PyLong_FromLong(grad_req_type != nullptr
                                       ? static_cast<long>(grad_req_type[i])
                                       : 0L));
  }
  PyObject* paux = PyList_New(aux_len);
  for (uint32_t i = 0; i < aux_len; ++i) {
    PyObject* a = static_cast<PyObject*>(aux_states[i]);
    Py_INCREF(a);
    PyList_SetItem(paux, i, a);
  }
  PyObject* args = Py_BuildValue("(ONNNN)", static_cast<PyObject*>(sym),
                                 pargs, pgrads, preqs, paux);
  PyObject* res = CallImpl("executor_bind", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXExecutorForward(ExecutorHandle h, int is_train) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(h), is_train);
  PyObject* res = CallImpl("executor_forward", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXExecutorOutputs(ExecutorHandle h, uint32_t* out_size,
                                NDArrayHandle** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(h));
  PyObject* res = CallImpl("executor_outputs", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(res, i);
    Py_INCREF(item);
    g_handle_store.push_back(item);
  }
  Py_DECREF(res);
  *out_size = static_cast<uint32_t>(n);
  *out = g_handle_store.data();
  return 0;
}

MXTPU_API int MXExecutorBackward(ExecutorHandle h, uint32_t len,
                                 NDArrayHandle* head_grads) {
  Gil gil;
  PyObject* pgrads = PyList_New(len);
  for (uint32_t i = 0; i < len; ++i) {
    PyObject* g = static_cast<PyObject*>(head_grads[i]);
    Py_INCREF(g);
    PyList_SetItem(pgrads, i, g);
  }
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(h), pgrads);
  PyObject* res = CallImpl("executor_backward", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXExecutorFree(ExecutorHandle h) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(h));
  return 0;
}

// ---------------------------------------------------------------------------
// Predict API (c_predict_api.h)
// ---------------------------------------------------------------------------

MXTPU_API int MXPredCreate(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes,
                           const char** input_keys,
                           const uint32_t* input_shape_indptr,
                           const uint32_t* input_shape_data,
                           PredictorHandle* out) {
  (void)dev_type; (void)dev_id;
  Gil gil;
  PyObject* names = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j) {
      PyList_SetItem(shp, j - lo, PyLong_FromUnsignedLong(
          input_shape_data[j]));
    }
    PyList_SetItem(shapes, i, shp);
  }
  PyObject* blob = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* args = Py_BuildValue("(sNNN)", symbol_json_str, blob, names,
                                 shapes);
  PyObject* res = CallImpl("pred_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXPredSetInput(PredictorHandle handle, const char* key,
                             const float* data, uint32_t size) {
  Gil gil;
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * 4);
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "set_input", "sN", key, bytes);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXPredForward(PredictorHandle handle) {
  Gil gil;
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "forward", nullptr);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                                   uint32_t** shape_data,
                                   uint32_t* shape_ndim) {
  Gil gil;
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "output_shape", "I", index);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_shape_buf[i] =
        static_cast<uint32_t>(PyLong_AsLong(PyList_GetItem(res, i)));
  }
  Py_DECREF(res);
  *shape_data = g_shape_buf.data();
  *shape_ndim = static_cast<uint32_t>(n);
  return 0;
}

MXTPU_API int MXPredGetOutput(PredictorHandle handle, uint32_t index,
                              float* data, uint32_t size) {
  Gil gil;
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "get_output", "I", index);
  if (res == nullptr) return FailFromPython();
  char* buf = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(res, &buf, &n);
  size_t want = static_cast<size_t>(size) * 4;
  std::memcpy(data, buf,
              want < static_cast<size_t>(n) ? want : static_cast<size_t>(n));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXPredFree(PredictorHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}
