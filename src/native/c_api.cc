// MXNet-compatible C ABI over the TPU-native runtime.
//
// Reference contract: include/mxnet/c_api.h (242 MXNET_DLL functions) and
// include/mxnet/c_predict_api.h:84-289 (serving ABI).  In the reference the
// C layer sits UNDER the Python frontend; here the compute runtime IS
// Python/JAX, so the C ABI is a native shim that drives the runtime through
// the embedded CPython API (incubator_mxnet_tpu.capi_impl does the
// marshalling).  Handles are strong PyObject references; every entry point
// takes the GIL, so the library is callable from any C/C++ thread — the
// same contract the reference's thread-safe predict API documents.
//
// Implemented surface (every subsystem a binding needs):
//   error     MXGetLastError, MXGetVersion
//   ndarray   MXNDArrayCreate/Ex, Free, SyncCopyFromCPU, SyncCopyToCPU,
//             GetShape, GetDType, WaitToRead, Save, Load, GetGrad, Detach,
//             Reshape, Slice, At, GetContext
//   ops       MXListAllOpNames, MXImperativeInvokeByName
//   symbol    MXSymbolCreateFromJSON, SaveToJSON, Free, ListArguments,
//             ListOutputs, ListAuxiliaryStates, CreateVariable,
//             CreateFromOp, InferShape(Partial), AtomicSymbol reflection
//   executor  MXExecutorBind, Forward, Outputs, Backward, Free
//   autograd  MXAutogradSetIsRecording/Training, IsRecording/Training,
//             MarkVariables, Backward(Ex), ComputeGradient
//   kvstore   MXKVStoreCreate, Free, Init(Ex), Push(Ex), Pull(Ex),
//             GetType/Rank/GroupSize, Barrier, Is*Node, SetUpdater
//             (C callback trampoline)
//   io        MXListDataIters, MXDataIterCreateIter/Free/Next/BeforeFirst/
//             GetData/GetLabel/GetPadNum/GetIndex
//   recordio  MXRecordIOWriter{Create,Free,WriteRecord,Tell},
//             MXRecordIOReader{Create,Free,ReadRecord,Seek,Tell}
//   cachedop  MXCreateCachedOp(Ex), MXFreeCachedOp, MXInvokeCachedOp(Ex)
//   misc      MXRandomSeed, MXEngineWaitAll, MXNotifyShutdown,
//             MXSetNumOMPThreads, MXStorageEmptyCache
//   predict   MXPredCreate, SetInput, Forward, GetOutputShape, GetOutput,
//             Free
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_set>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* PredictorHandle;

namespace {

thread_local std::string g_last_error;
// per-thread scratch keeping returned pointers alive until the next call
// (the reference uses MXAPIThreadLocalEntry the same way)
thread_local std::vector<uint32_t> g_shape_buf;
thread_local std::vector<std::string> g_str_store;
thread_local std::vector<const char*> g_ptr_store;
thread_local std::string g_json_buf;
thread_local std::vector<NDArrayHandle> g_handle_store;

int Fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

int FailFromPython() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      msg = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return Fail(msg);
}

// Bring up the interpreter once for standalone C/C++ consumers (no-op when
// the host process is already a live interpreter).  Must run BEFORE any
// PyGILState_Ensure: taking the GIL on an uninitialized runtime crashes.
void EnsureInterpreter() {
  static std::once_flag once;
  std::call_once(once, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // drop the GIL the init acquired so PyGILState_* manages it from
      // any caller thread
      PyEval_SaveThread();
    }
  });
}

class Gil {
 public:
  Gil() {
    EnsureInterpreter();
    state_ = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// Import the marshalling module (caller must hold the GIL).  No call_once:
// blocking in a foreign once while holding the GIL would deadlock against
// the importing thread (imports release the GIL mid-way); CPython's
// sys.modules makes repeat imports cheap and idempotent, and the import
// lock serializes racing first-imports correctly under the GIL.
PyObject* Impl() {
  PyObject* impl = PyImport_ImportModule("incubator_mxnet_tpu.capi_impl");
  if (impl == nullptr) PyErr_Print();
  return impl;
}

PyObject* CallImpl(const char* fn, PyObject* args) {
  PyObject* mod = Impl();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

int StoreStringList(PyObject* list, uint32_t* out_size,
                    const char*** out_array) {
  g_str_store.clear();
  g_ptr_store.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_str_store.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(list, i)));
  }
  for (auto& s : g_str_store) g_ptr_store.push_back(s.c_str());
  *out_size = static_cast<uint32_t>(n);
  *out_array = g_ptr_store.data();
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// error / version
// ---------------------------------------------------------------------------

MXTPU_API const char* MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXGetVersion(int* out) {
  *out = 10600;  // reports 1.6.0-compatible surface
  return 0;
}

// ---------------------------------------------------------------------------
// NDArray
// ---------------------------------------------------------------------------

MXTPU_API int MXNDArrayCreateEx(const uint32_t* shape, uint32_t ndim,
                                int dev_type, int dev_id, int delay_alloc,
                                int dtype, NDArrayHandle* out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i) {
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* args = Py_BuildValue("(Ni)", shp, dtype);
  PyObject* res = CallImpl("ndarray_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;  // strong reference transferred to the handle
  return 0;
}

MXTPU_API int MXNDArrayCreate(const uint32_t* shape, uint32_t ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              NDArrayHandle* out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

MXTPU_API int MXNDArrayFree(NDArrayHandle handle) {
  Gil gil;
  PyObject* h = static_cast<PyObject*>(handle);
  // last chance to sync writes made through a GetData pointer (shallow
  // copies share the object, so the data may outlive this handle)
  if (h != nullptr && PyObject_HasAttrString(h, "_capi_host_buf")) {
    PyObject* args = Py_BuildValue("(O)", h);
    PyObject* res = CallImpl("ndarray_writeback_host_buf", args);
    Py_DECREF(args);
    if (res == nullptr) PyErr_Clear();
    else Py_DECREF(res);
  }
  Py_XDECREF(h);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                                       size_t size) {
  Gil gil;
  // size is an element count in the reference ABI; bytes = count * itemsize
  PyObject* dt = PyObject_GetAttrString(static_cast<PyObject*>(handle),
                                        "dtype");
  if (dt == nullptr) return FailFromPython();
  PyObject* isz = PyObject_GetAttrString(dt, "itemsize");
  Py_DECREF(dt);
  if (isz == nullptr) return FailFromPython();
  size_t nbytes = size * PyLong_AsSize_t(isz);
  Py_DECREF(isz);
  PyObject* bytes = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 bytes);
  PyObject* res = CallImpl("ndarray_sync_copy_from", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                                     size_t size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_to_bytes", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  char* buf = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(res, &buf, &n);
  PyObject* dt = PyObject_GetAttrString(static_cast<PyObject*>(handle),
                                        "dtype");
  PyObject* isz = dt ? PyObject_GetAttrString(dt, "itemsize") : nullptr;
  size_t want = size * (isz ? PyLong_AsSize_t(isz) : 1);
  Py_XDECREF(dt);
  Py_XDECREF(isz);
  std::memcpy(data, buf, want < static_cast<size_t>(n) ? want
                                                       : static_cast<size_t>(n));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayWaitToRead(NDArrayHandle handle) {
  Gil gil;
  // routed through capi_impl so an outstanding GetData host buffer is
  // written back before the wait (raw-pointer write contract)
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_wait_to_read", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayGetShape(NDArrayHandle handle, uint32_t* out_dim,
                                const uint32_t** out_pdata) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_shape_buf[i] =
        static_cast<uint32_t>(PyLong_AsLong(PyList_GetItem(res, i)));
  }
  Py_DECREF(res);
  *out_dim = static_cast<uint32_t>(n);
  *out_pdata = g_shape_buf.data();
  return 0;
}

MXTPU_API int MXNDArrayGetDType(NDArrayHandle handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_dtype", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArraySave(const char* fname, uint32_t num_args,
                            NDArrayHandle* args_, const char** keys) {
  Gil gil;
  PyObject* handles = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    Py_INCREF(static_cast<PyObject*>(args_[i]));
    PyList_SetItem(handles, i, static_cast<PyObject*>(args_[i]));
  }
  PyObject* names;
  if (keys != nullptr) {
    names = PyList_New(num_args);
    for (uint32_t i = 0; i < num_args; ++i) {
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
    }
  } else {
    names = PyList_New(0);
  }
  PyObject* args = Py_BuildValue("(sNN)", fname, handles, names);
  PyObject* res = CallImpl("ndarray_save", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayLoad(const char* fname, uint32_t* out_size,
                            NDArrayHandle** out_arr, uint32_t* out_name_size,
                            const char*** out_names) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* res = CallImpl("ndarray_load", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  PyObject* arrs = PyTuple_GetItem(res, 0);
  PyObject* names = PyTuple_GetItem(res, 1);
  Py_ssize_t n = PyList_Size(arrs);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(arrs, i);
    Py_INCREF(item);
    g_handle_store.push_back(item);
  }
  *out_size = static_cast<uint32_t>(n);
  *out_arr = g_handle_store.data();
  StoreStringList(names, out_name_size, out_names);
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// ops
// ---------------------------------------------------------------------------

MXTPU_API int MXListAllOpNames(uint32_t* out_size, const char*** out_array) {
  Gil gil;
  PyObject* res = CallImpl("list_op_names", nullptr);
  if (res == nullptr) return FailFromPython();
  StoreStringList(res, out_size, out_array);
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXImperativeInvokeByName(
    const char* op_name, int num_inputs, NDArrayHandle* inputs,
    int* num_outputs, NDArrayHandle** outputs, int num_params,
    const char** param_keys, const char** param_vals) {
  Gil gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    Py_INCREF(static_cast<PyObject*>(inputs[i]));
    PyList_SetItem(ins, i, static_cast<PyObject*>(inputs[i]));
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  // caller-provided outputs = in-place write request (MXImperativeInvokeEx
  // contract, src/c_api/c_api_ndarray.cc:138)
  const bool provided = *outputs != nullptr && *num_outputs > 0;
  PyObject* pouts;
  if (provided) {
    pouts = PyList_New(*num_outputs);
    for (int i = 0; i < *num_outputs; ++i) {
      PyObject* o = static_cast<PyObject*>((*outputs)[i]);
      Py_INCREF(o);
      PyList_SetItem(pouts, i, o);
    }
  } else {
    pouts = Py_None;
    Py_INCREF(pouts);
  }
  PyObject* args = Py_BuildValue("(sNNNN)", op_name, ins, keys, vals, pouts);
  PyObject* res = CallImpl("imperative_invoke", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  if (provided) {  // results landed in the caller's handles
    Py_DECREF(res);
    return 0;
  }
  Py_ssize_t n = PyList_Size(res);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(res, i);
    Py_INCREF(item);
    g_handle_store.push_back(item);
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = g_handle_store.data();
  return 0;
}

// ---------------------------------------------------------------------------
// Symbol
// ---------------------------------------------------------------------------

MXTPU_API int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* res = CallImpl("symbol_from_json", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_to_json", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_json = g_json_buf.c_str();
  return 0;
}

MXTPU_API int MXSymbolFree(SymbolHandle sym) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(sym));
  return 0;
}

static int SymbolStrList(const char* fn, SymbolHandle sym, uint32_t* out_size,
                         const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl(fn, args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  StoreStringList(res, out_size, out_array);
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXSymbolListArguments(SymbolHandle sym, uint32_t* out_size,
                                    const char*** out_array) {
  return SymbolStrList("symbol_list_arguments", sym, out_size, out_array);
}

MXTPU_API int MXSymbolListOutputs(SymbolHandle sym, uint32_t* out_size,
                                  const char*** out_array) {
  return SymbolStrList("symbol_list_outputs", sym, out_size, out_array);
}

MXTPU_API int MXSymbolListAuxiliaryStates(SymbolHandle sym,
                                          uint32_t* out_size,
                                          const char*** out_array) {
  return SymbolStrList("symbol_list_aux", sym, out_size, out_array);
}

// Atomic-symbol creator reflection (MXSymbolListAtomicSymbolCreators +
// MXSymbolGetAtomicSymbolInfo, src/c_api/c_api_symbolic.cc) — the surface
// the reference code-gens every language binding's op wrappers from.
// Creator handles are interned op-name strings.
typedef void* AtomicSymbolCreator;

namespace {
thread_local std::vector<std::string> g_creator_names;
thread_local std::vector<AtomicSymbolCreator> g_creator_ptrs;
thread_local std::string g_info_name, g_info_desc;
thread_local std::vector<std::string> g_info_store[3];
thread_local std::vector<const char*> g_info_ptrs[3];
}  // namespace

MXTPU_API int MXSymbolListAtomicSymbolCreators(uint32_t* out_size,
                                               AtomicSymbolCreator** out) {
  Gil gil;
  PyObject* res = CallImpl("list_op_names", nullptr);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_creator_names.clear();
  g_creator_ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_creator_names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(res, i)));
  }
  Py_DECREF(res);
  for (auto& s : g_creator_names) {
    g_creator_ptrs.push_back(const_cast<char*>(s.c_str()));
  }
  *out_size = static_cast<uint32_t>(n);
  *out = g_creator_ptrs.data();
  return 0;
}

MXTPU_API int MXSymbolGetAtomicSymbolInfo(
    AtomicSymbolCreator creator, const char** name, const char** description,
    uint32_t* num_args, const char*** arg_names, const char*** arg_types,
    const char*** arg_descriptions) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", static_cast<const char*>(creator));
  PyObject* res = CallImpl("op_info_strings", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_info_name = PyUnicode_AsUTF8(PyTuple_GetItem(res, 0));
  g_info_desc = PyUnicode_AsUTF8(PyTuple_GetItem(res, 1));
  const char*** outs[3] = {arg_names, arg_types, arg_descriptions};
  uint32_t n = 0;
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GetItem(res, 2 + g);
    Py_ssize_t m = PyList_Size(lst);
    g_info_store[g].clear();
    g_info_ptrs[g].clear();
    for (Py_ssize_t i = 0; i < m; ++i) {
      g_info_store[g].emplace_back(PyUnicode_AsUTF8(PyList_GetItem(lst, i)));
    }
    for (auto& s : g_info_store[g]) g_info_ptrs[g].push_back(s.c_str());
    *outs[g] = g_info_ptrs[g].data();
    n = static_cast<uint32_t>(m);
  }
  Py_DECREF(res);
  *name = g_info_name.c_str();
  *description = g_info_desc.c_str();
  *num_args = n;
  return 0;
}

MXTPU_API int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  PyObject* res = CallImpl("symbol_create_variable", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

// One-shot CreateAtomicSymbol + Compose (src/c_api/c_api_symbolic.cc):
// builds the op node over named/positional input symbols.  input_keys may be
// nullptr (all positional) and individual entries may be nullptr.
MXTPU_API int MXSymbolCreateFromOp(const char* op_name, uint32_t num_params,
                                   const char** param_keys,
                                   const char** param_vals,
                                   uint32_t num_inputs,
                                   const char** input_keys,
                                   SymbolHandle* inputs, const char* name,
                                   SymbolHandle* out) {
  Gil gil;
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (uint32_t i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* in_names = PyList_New(num_inputs);
  PyObject* in_syms = PyList_New(num_inputs);
  for (uint32_t i = 0; i < num_inputs; ++i) {
    const char* k = input_keys != nullptr ? input_keys[i] : nullptr;
    PyList_SetItem(in_names, i,
                   k != nullptr ? PyUnicode_FromString(k)
                                : (Py_INCREF(Py_None), Py_None));
    PyObject* s = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(s);
    PyList_SetItem(in_syms, i, s);
  }
  PyObject* args = Py_BuildValue("(sNNNNs)", op_name, keys, vals, in_names,
                                 in_syms, name != nullptr ? name : "");
  PyObject* res = CallImpl("symbol_create_from_op", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

namespace {

// arena for MXSymbolInferShape outputs (alive until the next call on this
// thread, mirroring MXAPIThreadLocalEntry)
thread_local std::vector<std::vector<uint32_t>> g_is_shapes[3];
thread_local std::vector<uint32_t> g_is_ndim[3];
thread_local std::vector<const uint32_t*> g_is_ptr[3];

int StoreShapeGroup(PyObject* lst, int slot, uint32_t* out_size,
                    const uint32_t** out_ndim, const uint32_t*** out_data) {
  auto& shapes = g_is_shapes[slot];
  auto& ndims = g_is_ndim[slot];
  auto& ptrs = g_is_ptr[slot];
  shapes.clear();
  ndims.clear();
  ptrs.clear();
  Py_ssize_t n = PyList_Size(lst);
  shapes.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* shp = PyList_GetItem(lst, i);
    Py_ssize_t nd = PyList_Size(shp);
    for (Py_ssize_t d = 0; d < nd; ++d) {
      shapes[i].push_back(static_cast<uint32_t>(
          PyLong_AsLong(PyList_GetItem(shp, d))));
    }
    ndims.push_back(static_cast<uint32_t>(nd));
  }
  for (auto& s : shapes) ptrs.push_back(s.data());
  *out_size = static_cast<uint32_t>(n);
  *out_ndim = ndims.data();
  *out_data = ptrs.data();
  return 0;
}

int InferShapeImpl(SymbolHandle sym, uint32_t num_args, const char** keys,
                   const uint32_t* arg_ind_ptr,
                   const uint32_t* arg_shape_data, uint32_t* in_size,
                   const uint32_t** in_ndim, const uint32_t*** in_data,
                   uint32_t* out_size, const uint32_t** out_ndim,
                   const uint32_t*** out_data, uint32_t* aux_size,
                   const uint32_t** aux_ndim, const uint32_t*** aux_data,
                   int* complete, int partial) {
  Gil gil;
  PyObject* pkeys = PyList_New(num_args);
  PyObject* pshapes = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(keys[i]));
    uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (uint32_t d = lo; d < hi; ++d) {
      PyList_SetItem(shp, d - lo, PyLong_FromLong(arg_shape_data[d]));
    }
    PyList_SetItem(pshapes, i, shp);
  }
  PyObject* args = Py_BuildValue("(ONNi)", static_cast<PyObject*>(sym),
                                 pkeys, pshapes, partial);
  PyObject* res = CallImpl("symbol_infer_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  StoreShapeGroup(PyTuple_GetItem(res, 0), 0, in_size, in_ndim, in_data);
  StoreShapeGroup(PyTuple_GetItem(res, 1), 1, out_size, out_ndim, out_data);
  StoreShapeGroup(PyTuple_GetItem(res, 2), 2, aux_size, aux_ndim, aux_data);
  *complete = PyObject_IsTrue(PyTuple_GetItem(res, 3));
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXSymbolInferShape(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete) {
  return InferShapeImpl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                        in_shape_size, in_shape_ndim, in_shape_data,
                        out_shape_size, out_shape_ndim, out_shape_data,
                        aux_shape_size, aux_shape_ndim, aux_shape_data,
                        complete, 0);
}

MXTPU_API int MXSymbolInferShapePartial(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete) {
  return InferShapeImpl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                        in_shape_size, in_shape_ndim, in_shape_data,
                        out_shape_size, out_shape_ndim, out_shape_data,
                        aux_shape_size, aux_shape_ndim, aux_shape_data,
                        complete, 1);
}

// ---------------------------------------------------------------------------
// Executor (MXExecutorBind family, include/mxnet/c_api.h)
// ---------------------------------------------------------------------------

typedef void* ExecutorHandle;

MXTPU_API int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                             uint32_t len, NDArrayHandle* in_args,
                             NDArrayHandle* arg_grad_store,
                             uint32_t* grad_req_type, uint32_t aux_len,
                             NDArrayHandle* aux_states, ExecutorHandle* out) {
  (void)dev_type;
  (void)dev_id;
  Gil gil;
  PyObject* pargs = PyList_New(len);
  PyObject* pgrads = PyList_New(len);
  PyObject* preqs = PyList_New(len);
  for (uint32_t i = 0; i < len; ++i) {
    PyObject* a = static_cast<PyObject*>(in_args[i]);
    Py_INCREF(a);
    PyList_SetItem(pargs, i, a);
    PyObject* g = arg_grad_store != nullptr && arg_grad_store[i] != nullptr
                      ? static_cast<PyObject*>(arg_grad_store[i])
                      : Py_None;
    Py_INCREF(g);
    PyList_SetItem(pgrads, i, g);
    PyList_SetItem(preqs, i,
                   PyLong_FromLong(grad_req_type != nullptr
                                       ? static_cast<long>(grad_req_type[i])
                                       : 0L));
  }
  PyObject* paux = PyList_New(aux_len);
  for (uint32_t i = 0; i < aux_len; ++i) {
    PyObject* a = static_cast<PyObject*>(aux_states[i]);
    Py_INCREF(a);
    PyList_SetItem(paux, i, a);
  }
  PyObject* args = Py_BuildValue("(ONNNN)", static_cast<PyObject*>(sym),
                                 pargs, pgrads, preqs, paux);
  PyObject* res = CallImpl("executor_bind", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXExecutorForward(ExecutorHandle h, int is_train) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(h), is_train);
  PyObject* res = CallImpl("executor_forward", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXExecutorOutputs(ExecutorHandle h, uint32_t* out_size,
                                NDArrayHandle** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(h));
  PyObject* res = CallImpl("executor_outputs", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(res, i);
    Py_INCREF(item);
    g_handle_store.push_back(item);
  }
  Py_DECREF(res);
  *out_size = static_cast<uint32_t>(n);
  *out = g_handle_store.data();
  return 0;
}

MXTPU_API int MXExecutorBackward(ExecutorHandle h, uint32_t len,
                                 NDArrayHandle* head_grads) {
  Gil gil;
  PyObject* pgrads = PyList_New(len);
  for (uint32_t i = 0; i < len; ++i) {
    PyObject* g = static_cast<PyObject*>(head_grads[i]);
    Py_INCREF(g);
    PyList_SetItem(pgrads, i, g);
  }
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(h), pgrads);
  PyObject* res = CallImpl("executor_backward", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXExecutorFree(ExecutorHandle h) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(h));
  return 0;
}

// ---------------------------------------------------------------------------
// Predict API (c_predict_api.h)
// ---------------------------------------------------------------------------

MXTPU_API int MXPredCreate(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id,
                           uint32_t num_input_nodes,
                           const char** input_keys,
                           const uint32_t* input_shape_indptr,
                           const uint32_t* input_shape_data,
                           PredictorHandle* out) {
  (void)dev_type; (void)dev_id;
  Gil gil;
  PyObject* names = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j) {
      PyList_SetItem(shp, j - lo, PyLong_FromUnsignedLong(
          input_shape_data[j]));
    }
    PyList_SetItem(shapes, i, shp);
  }
  PyObject* blob = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* args = Py_BuildValue("(sNNN)", symbol_json_str, blob, names,
                                 shapes);
  PyObject* res = CallImpl("pred_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXPredSetInput(PredictorHandle handle, const char* key,
                             const float* data, uint32_t size) {
  Gil gil;
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * 4);
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "set_input", "sN", key, bytes);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXPredForward(PredictorHandle handle) {
  Gil gil;
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "forward", nullptr);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                                   uint32_t** shape_data,
                                   uint32_t* shape_ndim) {
  Gil gil;
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "output_shape", "I", index);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_shape_buf[i] =
        static_cast<uint32_t>(PyLong_AsLong(PyList_GetItem(res, i)));
  }
  Py_DECREF(res);
  *shape_data = g_shape_buf.data();
  *shape_ndim = static_cast<uint32_t>(n);
  return 0;
}

MXTPU_API int MXPredGetOutput(PredictorHandle handle, uint32_t index,
                              float* data, uint32_t size) {
  Gil gil;
  PyObject* res = PyObject_CallMethod(static_cast<PyObject*>(handle),
                                      "get_output", "I", index);
  if (res == nullptr) return FailFromPython();
  char* buf = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(res, &buf, &n);
  size_t want = static_cast<size_t>(size) * 4;
  std::memcpy(data, buf,
              want < static_cast<size_t>(n) ? want : static_cast<size_t>(n));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXPredFree(PredictorHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

// ---------------------------------------------------------------------------
// autograd (MXAutograd*: c_api.h autograd block)
// ---------------------------------------------------------------------------

namespace {

// call a 0/1-arg impl fn returning an int
int CallIntImpl(const char* fn, PyObject* args, int* out) {
  Gil gil;
  PyObject* res = CallImpl(fn, args);
  Py_XDECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXAutogradSetIsRecording(int is_recording, int* prev) {
  Gil gil;  // args must be built under the GIL
  return CallIntImpl("autograd_set_recording",
                     Py_BuildValue("(i)", is_recording), prev);
}

MXTPU_API int MXAutogradSetIsTraining(int is_training, int* prev) {
  Gil gil;
  return CallIntImpl("autograd_set_training",
                     Py_BuildValue("(i)", is_training), prev);
}

MXTPU_API int MXAutogradIsRecording(bool* curr) {
  Gil gil;
  int v = 0;
  int rc = CallIntImpl("autograd_is_recording", PyTuple_New(0), &v);
  *curr = v != 0;
  return rc;
}

MXTPU_API int MXAutogradIsTraining(bool* curr) {
  Gil gil;
  int v = 0;
  int rc = CallIntImpl("autograd_is_training", PyTuple_New(0), &v);
  *curr = v != 0;
  return rc;
}

MXTPU_API int MXAutogradMarkVariables(uint32_t num_var,
                                      NDArrayHandle* var_handles,
                                      uint32_t* reqs_array,
                                      NDArrayHandle* grad_handles) {
  Gil gil;
  PyObject* vars = PyList_New(num_var);
  PyObject* reqs = PyList_New(num_var);
  PyObject* grads = PyList_New(num_var);
  for (uint32_t i = 0; i < num_var; ++i) {
    PyObject* v = static_cast<PyObject*>(var_handles[i]);
    Py_INCREF(v);
    PyList_SetItem(vars, i, v);
    PyList_SetItem(reqs, i, PyLong_FromLong(reqs_array[i]));
    PyObject* g = static_cast<PyObject*>(grad_handles[i]);
    Py_INCREF(g);
    PyList_SetItem(grads, i, g);
  }
  PyObject* args = PyTuple_Pack(3, vars, reqs, grads);
  Py_DECREF(vars);
  Py_DECREF(reqs);
  Py_DECREF(grads);
  PyObject* res = CallImpl("autograd_mark_variables", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

namespace {

int AutogradBackwardImpl(uint32_t num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles, int retain_graph,
                         int train_mode) {
  Gil gil;
  PyObject* outs = PyList_New(num_output);
  for (uint32_t i = 0; i < num_output; ++i) {
    PyObject* o = static_cast<PyObject*>(output_handles[i]);
    Py_INCREF(o);
    PyList_SetItem(outs, i, o);
  }
  PyObject* ograds = Py_None;
  Py_INCREF(Py_None);
  if (ograd_handles != nullptr) {
    bool any = false;
    for (uint32_t i = 0; i < num_output; ++i) {
      if (ograd_handles[i] != nullptr) any = true;
    }
    if (any) {
      Py_DECREF(Py_None);
      ograds = PyList_New(num_output);
      for (uint32_t i = 0; i < num_output; ++i) {
        PyObject* g = static_cast<PyObject*>(ograd_handles[i]);
        Py_INCREF(g);
        PyList_SetItem(ograds, i, g);
      }
    }
  }
  PyObject* args = Py_BuildValue("(OOii)", outs, ograds, retain_graph,
                                 train_mode);
  Py_DECREF(outs);
  Py_DECREF(ograds);
  PyObject* res = CallImpl("autograd_backward", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out);

MXTPU_API int MXAutogradBackward(uint32_t num_output,
                                 NDArrayHandle* output_handles,
                                 NDArrayHandle* ograd_handles,
                                 int retain_graph) {
  return AutogradBackwardImpl(num_output, output_handles, ograd_handles,
                              retain_graph, 1);
}

MXTPU_API int MXAutogradBackwardEx(uint32_t num_output,
                                   NDArrayHandle* output_handles,
                                   NDArrayHandle* ograd_handles,
                                   uint32_t num_variables,
                                   NDArrayHandle* var_handles,
                                   int retain_graph, int create_graph,
                                   int is_train, NDArrayHandle** grad_handles,
                                   int** grad_stypes) {
  (void)create_graph;  // higher-order via python autograd only
  int rc = AutogradBackwardImpl(num_output, output_handles, ograd_handles,
                                retain_graph, is_train);
  if (rc != 0) return rc;
  if (grad_handles != nullptr) *grad_handles = nullptr;
  if (grad_stypes != nullptr) *grad_stypes = nullptr;
  if (num_variables > 0 && var_handles != nullptr &&
      grad_handles != nullptr) {
    Gil gil;
    g_handle_store.clear();
    static thread_local std::vector<int> stypes;
    stypes.assign(num_variables, 0);  // dense
    for (uint32_t i = 0; i < num_variables; ++i) {
      NDArrayHandle g = nullptr;
      rc = MXNDArrayGetGrad(var_handles[i], &g);
      if (rc != 0) return rc;
      g_handle_store.push_back(g);
    }
    *grad_handles = g_handle_store.data();
    if (grad_stypes != nullptr) *grad_stypes = stypes.data();
  }
  return 0;
}

MXTPU_API int MXAutogradComputeGradient(uint32_t num_output,
                                        NDArrayHandle* output_handles) {
  return AutogradBackwardImpl(num_output, output_handles, nullptr, 0, 1);
}

MXTPU_API int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_get_grad", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;  // strong reference becomes the handle
  return 0;
}

MXTPU_API int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_detach", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayReshape(NDArrayHandle handle, int ndim, int* dims,
                               NDArrayHandle* out) {
  Gil gil;
  PyObject* shape = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyList_SetItem(shape, i, PyLong_FromLong(dims[i]));
  }
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 shape);
  PyObject* res = CallImpl("ndarray_reshape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArraySlice(NDArrayHandle handle, uint32_t begin,
                             uint32_t end, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OII)", static_cast<PyObject*>(handle),
                                 begin, end);
  PyObject* res = CallImpl("ndarray_slice", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayAt(NDArrayHandle handle, uint32_t idx,
                          NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(handle), idx);
  PyObject* res = CallImpl("ndarray_at", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                                  int* out_dev_id) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_context", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out_dev_type = static_cast<int>(
      PyLong_AsLong(PyTuple_GetItem(res, 0)));
  *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 1)));
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// KVStore (MXKVStore*: c_api.h kvstore block)
// ---------------------------------------------------------------------------

typedef void* KVStoreHandle;
typedef void(MXKVStoreUpdater)(int key, NDArrayHandle recv,
                               NDArrayHandle local, void* handle);
typedef void(MXKVStoreStrUpdater)(const char* key, NDArrayHandle recv,
                                  NDArrayHandle local, void* handle);

namespace {

struct UpdaterClosure {
  MXKVStoreUpdater* fn;
  void* handle;
};

// PyCFunction trampoline: capi_impl's updater wrapper calls this with
// (capsule, key, recv, local) so the user's C function pointer runs with
// live NDArray handles. Ownership of both handles transfers to the
// callee (reference contract: the frontend wrapper wraps recv and local
// in owning NDArrays that call MXNDArrayFree on destruction).
PyObject* CallCUpdater(PyObject*, PyObject* args) {
  PyObject* capsule = nullptr;
  PyObject* key_obj = nullptr;
  PyObject* recv = nullptr;
  PyObject* local = nullptr;
  if (!PyArg_ParseTuple(args, "OOOO", &capsule, &key_obj, &recv, &local)) {
    return nullptr;
  }
  // int keys pass through; numeric strings (InitEx/PushEx path) convert —
  // a C MXKVStoreUpdater only carries int keys (c_api.h)
  long key = 0;
  if (PyLong_Check(key_obj)) {
    key = PyLong_AsLong(key_obj);
  } else if (PyUnicode_Check(key_obj)) {
    PyObject* as_int = PyLong_FromUnicodeObject(key_obj, 10);
    if (as_int == nullptr) {
      PyErr_SetString(PyExc_TypeError,
                      "C kvstore updater requires integer keys; use string "
                      "keys only with a python-level updater");
      return nullptr;
    }
    key = PyLong_AsLong(as_int);
    Py_DECREF(as_int);
  }
  auto* cl = static_cast<UpdaterClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_updater"));
  if (cl == nullptr) return nullptr;
  Py_INCREF(recv);
  Py_INCREF(local);
  cl->fn(static_cast<int>(key), recv, local, cl->handle);
  Py_RETURN_NONE;
}

PyMethodDef g_call_c_updater_def = {
    "call_c_updater", CallCUpdater, METH_VARARGS,
    "trampoline into a C MXKVStoreUpdater"};

void FreeUpdaterCapsule(PyObject* capsule) {
  delete static_cast<UpdaterClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_updater"));
}

int HandlesToList(uint32_t n, NDArrayHandle* hs, PyObject** out) {
  PyObject* list = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyObject* h = static_cast<PyObject*>(hs[i]);
    Py_INCREF(h);
    PyList_SetItem(list, i, h);
  }
  *out = list;
  return 0;
}

PyObject* IntKeysToList(uint32_t n, const int* keys) {
  PyObject* list = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyList_SetItem(list, i, PyLong_FromLong(keys[i]));
  }
  return list;
}

PyObject* StrKeysToList(uint32_t n, const char** keys) {
  PyObject* list = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyList_SetItem(list, i, PyUnicode_FromString(keys[i]));
  }
  return list;
}

int KVCall3(const char* fn, KVStoreHandle kv, PyObject* keys, uint32_t num,
            NDArrayHandle* vals, int priority, bool with_priority) {
  PyObject* hlist = nullptr;
  HandlesToList(num, vals, &hlist);
  PyObject* args = with_priority
      ? Py_BuildValue("(ONNi)", static_cast<PyObject*>(kv), keys, hlist,
                      priority)
      : Py_BuildValue("(ONN)", static_cast<PyObject*>(kv), keys, hlist);
  PyObject* res = CallImpl(fn, args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", type == nullptr ? "local" : type);
  PyObject* res = CallImpl("kvstore_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXKVStoreFree(KVStoreHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXKVStoreInit(KVStoreHandle kv, uint32_t num, const int* keys,
                            NDArrayHandle* vals) {
  Gil gil;
  return KVCall3("kvstore_init", kv, IntKeysToList(num, keys), num, vals, 0,
                 false);
}

MXTPU_API int MXKVStoreInitEx(KVStoreHandle kv, uint32_t num,
                              const char** keys, NDArrayHandle* vals) {
  Gil gil;
  return KVCall3("kvstore_init", kv, StrKeysToList(num, keys), num, vals, 0,
                 false);
}

MXTPU_API int MXKVStorePush(KVStoreHandle kv, uint32_t num, const int* keys,
                            NDArrayHandle* vals, int priority) {
  Gil gil;
  return KVCall3("kvstore_push", kv, IntKeysToList(num, keys), num, vals,
                 priority, true);
}

MXTPU_API int MXKVStorePushEx(KVStoreHandle kv, uint32_t num,
                              const char** keys, NDArrayHandle* vals,
                              int priority) {
  Gil gil;
  return KVCall3("kvstore_push", kv, StrKeysToList(num, keys), num, vals,
                 priority, true);
}

MXTPU_API int MXKVStorePull(KVStoreHandle kv, uint32_t num, const int* keys,
                            NDArrayHandle* vals, int priority) {
  Gil gil;
  return KVCall3("kvstore_pull", kv, IntKeysToList(num, keys), num, vals,
                 priority, true);
}

MXTPU_API int MXKVStorePullEx(KVStoreHandle kv, uint32_t num,
                              const char** keys, NDArrayHandle* vals,
                              int priority) {
  Gil gil;
  return KVCall3("kvstore_pull", kv, StrKeysToList(num, keys), num, vals,
                 priority, true);
}

MXTPU_API int MXKVStoreGetType(KVStoreHandle kv, const char** type) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallImpl("kvstore_type", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *type = g_json_buf.c_str();
  return 0;
}

MXTPU_API int MXKVStoreGetRank(KVStoreHandle kv, int* rank) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  return CallIntImpl("kvstore_rank", args, rank);
}

MXTPU_API int MXKVStoreGetGroupSize(KVStoreHandle kv, int* size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  return CallIntImpl("kvstore_group_size", args, size);
}

MXTPU_API int MXKVStoreBarrier(KVStoreHandle kv) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallImpl("kvstore_barrier", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXKVStoreIsWorkerNode(int* ret) {
  *ret = 1;
  return 0;
}

MXTPU_API int MXKVStoreIsServerNode(int* ret) {
  *ret = 0;
  return 0;
}

MXTPU_API int MXKVStoreIsSchedulerNode(int* ret) {
  *ret = 0;
  return 0;
}

MXTPU_API int MXKVStoreSetUpdater(KVStoreHandle kv, MXKVStoreUpdater updater,
                                  void* updater_handle) {
  Gil gil;
  auto* cl = new UpdaterClosure{updater, updater_handle};
  PyObject* capsule = PyCapsule_New(cl, "mxtpu_updater", FreeUpdaterCapsule);
  PyObject* tramp = PyCFunction_New(&g_call_c_updater_def, nullptr);
  // partial(call_c_updater, capsule) built in python for simplicity
  PyObject* functools = PyImport_ImportModule("functools");
  PyObject* partial = PyObject_GetAttrString(functools, "partial");
  PyObject* bound = PyObject_CallFunctionObjArgs(partial, tramp, capsule,
                                                 nullptr);
  Py_DECREF(functools);
  Py_DECREF(partial);
  Py_DECREF(tramp);
  Py_DECREF(capsule);
  if (bound == nullptr) return FailFromPython();
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(kv), bound);
  PyObject* res = CallImpl("kvstore_set_updater", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// DataIter (MXDataIter*: c_api.h io block)
// ---------------------------------------------------------------------------

typedef void* DataIterHandle;

MXTPU_API int MXListDataIters(uint32_t* out_size, const char*** out_array) {
  Gil gil;
  PyObject* res = CallImpl("list_data_iters", PyTuple_New(0));
  if (res == nullptr) return FailFromPython();
  StoreStringList(res, out_size, out_array);
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXDataIterCreateIter(const char* name, uint32_t num_param,
                                   const char** keys, const char** vals,
                                   DataIterHandle* out) {
  Gil gil;
  PyObject* k = StrKeysToList(num_param, keys);
  PyObject* v = StrKeysToList(num_param, vals);
  PyObject* args = Py_BuildValue("(sNN)", name, k, v);
  PyObject* res = CallImpl("data_iter_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXDataIterFree(DataIterHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXDataIterNext(DataIterHandle handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  return CallIntImpl("data_iter_next", args, out);
}

MXTPU_API int MXDataIterBeforeFirst(DataIterHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("data_iter_before_first", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

namespace {

int DataIterGet(const char* fn, DataIterHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl(fn, args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

}  // namespace

MXTPU_API int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out) {
  return DataIterGet("data_iter_data", handle, out);
}

MXTPU_API int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out) {
  return DataIterGet("data_iter_label", handle, out);
}

MXTPU_API int MXDataIterGetPadNum(DataIterHandle handle, int* pad) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  return CallIntImpl("data_iter_pad", args, pad);
}

MXTPU_API int MXDataIterGetIndex(DataIterHandle handle, uint64_t** out_index,
                                 uint64_t* out_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("data_iter_index", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  char* buf = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(res, &buf, &n);
  static thread_local std::vector<uint64_t> idx_buf;
  idx_buf.assign(reinterpret_cast<uint64_t*>(buf),
                 reinterpret_cast<uint64_t*>(buf) + n / 8);
  Py_DECREF(res);
  *out_index = idx_buf.data();
  *out_size = idx_buf.size();
  return 0;
}

// ---------------------------------------------------------------------------
// RecordIO (MXRecordIO*: c_api.h recordio block)
// ---------------------------------------------------------------------------

typedef void* RecordIOHandle;

MXTPU_API int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", uri);
  PyObject* res = CallImpl("recordio_writer_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXRecordIOWriterFree(RecordIOHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("recordio_writer_free", args);
  Py_DECREF(args);
  Py_XDECREF(static_cast<PyObject*>(handle));
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXRecordIOWriterWriteRecord(RecordIOHandle handle,
                                          const char* buf, size_t size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oy#)", static_cast<PyObject*>(handle),
                                 buf, static_cast<Py_ssize_t>(size));
  PyObject* res = CallImpl("recordio_writer_write", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

namespace {

int CallSizeImpl(const char* fn, PyObject* args, size_t* out) {
  Gil gil;
  PyObject* res = CallImpl(fn, args);
  Py_XDECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = static_cast<size_t>(PyLong_AsUnsignedLongLong(res));
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXRecordIOWriterTell(RecordIOHandle handle, size_t* pos) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  return CallSizeImpl("recordio_writer_tell", args, pos);
}

MXTPU_API int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", uri);
  PyObject* res = CallImpl("recordio_reader_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXRecordIOReaderFree(RecordIOHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("recordio_reader_free", args);
  Py_DECREF(args);
  Py_XDECREF(static_cast<PyObject*>(handle));
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXRecordIOReaderReadRecord(RecordIOHandle handle,
                                         char const** buf, size_t* size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("recordio_reader_read", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  if (res == Py_None) {
    Py_DECREF(res);
    *buf = nullptr;
    *size = 0;
    return 0;
  }
  char* b = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(res, &b, &n);
  g_json_buf.assign(b, static_cast<size_t>(n));
  Py_DECREF(res);
  *buf = g_json_buf.data();
  *size = g_json_buf.size();
  return 0;
}

MXTPU_API int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos) {
  Gil gil;
  PyObject* args = Py_BuildValue("(On)", static_cast<PyObject*>(handle),
                                 static_cast<Py_ssize_t>(pos));
  PyObject* res = CallImpl("recordio_reader_seek", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXRecordIOReaderTell(RecordIOHandle handle, size_t* pos) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  return CallSizeImpl("recordio_reader_tell", args, pos);
}

// ---------------------------------------------------------------------------
// CachedOp (MXCreateCachedOp / MXInvokeCachedOp)
// ---------------------------------------------------------------------------

typedef void* CachedOpHandle;

MXTPU_API int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("cached_op_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXCreateCachedOpEx(SymbolHandle sym, int num_flags,
                                 const char** keys, const char** vals,
                                 CachedOpHandle* out) {
  (void)num_flags;
  (void)keys;
  (void)vals;  // flags (static_alloc etc.) are no-ops: XLA owns buffers
  return MXCreateCachedOp(sym, out);
}

MXTPU_API int MXFreeCachedOp(CachedOpHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                               NDArrayHandle* inputs, int* num_outputs,
                               NDArrayHandle** outputs) {
  Gil gil;
  PyObject* ins = nullptr;
  HandlesToList(num_inputs, inputs, &ins);
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 ins);
  PyObject* res = CallImpl("cached_op_invoke", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(res, i);
    Py_INCREF(o);
    g_handle_store.push_back(o);
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = g_handle_store.data();
  return 0;
}

MXTPU_API int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                                 NDArrayHandle* inputs, int* num_outputs,
                                 NDArrayHandle** outputs,
                                 const int** out_stypes) {
  static thread_local std::vector<int> stypes;
  int rc = MXInvokeCachedOp(handle, num_inputs, inputs, num_outputs, outputs);
  if (rc != 0) return rc;
  stypes.assign(static_cast<size_t>(*num_outputs), 0);  // dense
  *out_stypes = stypes.data();
  return 0;
}

// ---------------------------------------------------------------------------
// misc runtime
// ---------------------------------------------------------------------------

MXTPU_API int MXRandomSeed(int seed) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  PyObject* res = CallImpl("random_seed", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXEngineWaitAll() {
  Gil gil;
  PyObject* res = CallImpl("engine_wait_all", PyTuple_New(0));
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNotifyShutdown() { return 0; }

MXTPU_API int MXSetNumOMPThreads(int n) {
  (void)n;  // XLA owns its own thread pools
  return 0;
}

MXTPU_API int MXStorageEmptyCache(int dev_type, int dev_id) {
  (void)dev_type;
  (void)dev_id;  // XLA allocator; nothing to flush
  return 0;
}

// ---------------------------------------------------------------------------
// Profiler (MXProfile* / MXSetProfilerConfig: c_api.h profiler block;
// reference impl src/c_api/c_api_profile.cc)
// ---------------------------------------------------------------------------

typedef void* ProfileHandle;

namespace {

int ProfileCreate(const char* fn, PyObject* args, ProfileHandle* out) {
  PyObject* res = CallImpl(fn, args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

int CallVoidImpl(const char* fn, PyObject* args) {
  PyObject* res = CallImpl(fn, args);
  Py_XDECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXSetProfilerConfig(int num_params, const char* const* keys,
                                  const char* const* vals) {
  Gil gil;
  PyObject* k = PyList_New(num_params);
  PyObject* v = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
    PyList_SetItem(v, i, PyUnicode_FromString(vals[i]));
  }
  return CallVoidImpl("profiler_set_config", Py_BuildValue("(NN)", k, v));
}

MXTPU_API int MXSetProfilerState(int state) {
  Gil gil;
  return CallVoidImpl("profiler_set_state", Py_BuildValue("(i)", state));
}

MXTPU_API int MXProfilePause(int profile_process) {
  Gil gil;
  return CallVoidImpl("profiler_pause",
                      Py_BuildValue("(i)", profile_process));
}

MXTPU_API int MXProfileResume(int profile_process) {
  Gil gil;
  return CallVoidImpl("profiler_resume",
                      Py_BuildValue("(i)", profile_process));
}

MXTPU_API int MXDumpProfile(int finished) {
  Gil gil;
  return CallVoidImpl("profiler_dump", Py_BuildValue("(ii)", finished, 0));
}

MXTPU_API int MXAggregateProfileStatsPrint(const char** out_str, int reset) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", reset);
  PyObject* res = CallImpl("profiler_dumps", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_str = g_json_buf.c_str();
  return 0;
}

MXTPU_API int MXProfileCreateDomain(const char* domain, ProfileHandle* out) {
  Gil gil;
  return ProfileCreate("profile_create_domain",
                       Py_BuildValue("(s)", domain), out);
}

MXTPU_API int MXProfileCreateTask(ProfileHandle domain, const char* name,
                                  ProfileHandle* out) {
  Gil gil;
  return ProfileCreate("profile_create_task",
                       Py_BuildValue("(Os)",
                                     static_cast<PyObject*>(domain), name),
                       out);
}

MXTPU_API int MXProfileCreateFrame(ProfileHandle domain, const char* name,
                                   ProfileHandle* out) {
  Gil gil;
  return ProfileCreate("profile_create_frame",
                       Py_BuildValue("(Os)",
                                     static_cast<PyObject*>(domain), name),
                       out);
}

MXTPU_API int MXProfileCreateEvent(const char* name, ProfileHandle* out) {
  Gil gil;
  return ProfileCreate("profile_create_event", Py_BuildValue("(s)", name),
                       out);
}

MXTPU_API int MXProfileCreateCounter(ProfileHandle domain, const char* name,
                                     ProfileHandle* out) {
  Gil gil;
  return ProfileCreate("profile_create_counter",
                       Py_BuildValue("(Os)",
                                     static_cast<PyObject*>(domain), name),
                       out);
}

MXTPU_API int MXProfileDestroyHandle(ProfileHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXProfileDurationStart(ProfileHandle duration) {
  Gil gil;
  return CallVoidImpl(
      "profile_duration_start",
      Py_BuildValue("(O)", static_cast<PyObject*>(duration)));
}

MXTPU_API int MXProfileDurationStop(ProfileHandle duration) {
  Gil gil;
  return CallVoidImpl(
      "profile_duration_stop",
      Py_BuildValue("(O)", static_cast<PyObject*>(duration)));
}

MXTPU_API int MXProfileSetCounter(ProfileHandle counter, uint64_t value) {
  Gil gil;
  return CallVoidImpl(
      "profile_set_counter",
      Py_BuildValue("(OK)", static_cast<PyObject*>(counter),
                    static_cast<unsigned long long>(value)));
}

MXTPU_API int MXProfileAdjustCounter(ProfileHandle counter, int64_t delta) {
  Gil gil;
  return CallVoidImpl(
      "profile_adjust_counter",
      Py_BuildValue("(OL)", static_cast<PyObject*>(counter),
                    static_cast<long long>(delta)));
}

MXTPU_API int MXProfileSetMarker(ProfileHandle domain, const char* name,
                                 const char* scope) {
  Gil gil;
  return CallVoidImpl(
      "profile_set_marker",
      Py_BuildValue("(Oss)", static_cast<PyObject*>(domain), name,
                    scope == nullptr ? "process" : scope));
}

// ---------------------------------------------------------------------------
// Legacy function registry (MXListFunctions / MXFunc*: c_api.h)
// ---------------------------------------------------------------------------

typedef void* FunctionHandle;

MXTPU_API int MXListFunctions(uint32_t* out_size, FunctionHandle** out_array) {
  Gil gil;
  PyObject* res = CallImpl("list_functions", PyTuple_New(0));
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* s = PyList_GetItem(res, i);
    Py_INCREF(s);
    g_handle_store.push_back(s);  // handle == interned op-name string
  }
  Py_DECREF(res);
  *out_size = static_cast<uint32_t>(n);
  *out_array = g_handle_store.data();
  return 0;
}

MXTPU_API int MXFuncGetInfo(FunctionHandle fun, const char** name,
                            const char** description, uint32_t* num_args,
                            const char*** arg_names,
                            const char*** arg_type_infos,
                            const char*** arg_descriptions,
                            const char** return_type) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(fun));
  PyObject* res = CallImpl("func_info", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  static thread_local std::vector<std::string> strs;
  static thread_local std::vector<const char*> names_p, types_p, descs_p;
  strs.clear();
  names_p.clear();
  types_p.clear();
  descs_p.clear();
  strs.emplace_back(PyUnicode_AsUTF8(PyTuple_GetItem(res, 0)));
  strs.emplace_back(PyUnicode_AsUTF8(PyTuple_GetItem(res, 1)));
  PyObject* ins = PyTuple_GetItem(res, 2);
  PyObject* arg_n = PyTuple_GetItem(res, 3);
  PyObject* arg_t = PyTuple_GetItem(res, 4);
  size_t base = strs.size();
  for (Py_ssize_t i = 0; i < PyList_Size(ins); ++i) {
    strs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(ins, i)));
  }
  size_t n_in = PyList_Size(ins);
  for (Py_ssize_t i = 0; i < PyList_Size(arg_n); ++i) {
    strs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(arg_n, i)));
  }
  for (Py_ssize_t i = 0; i < PyList_Size(arg_t); ++i) {
    strs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(arg_t, i)));
  }
  size_t n_attr = PyList_Size(arg_n);
  for (size_t i = 0; i < n_in + n_attr; ++i) {
    names_p.push_back(strs[base + i].c_str());
    types_p.push_back(i < n_in ? "NDArray"
                               : strs[base + n_in + n_attr +
                                      (i - n_in)].c_str());
    descs_p.push_back("");
  }
  Py_DECREF(res);
  *name = strs[0].c_str();
  *description = strs[1].c_str();
  *num_args = static_cast<uint32_t>(n_in + n_attr);
  *arg_names = names_p.data();
  *arg_type_infos = types_p.data();
  *arg_descriptions = descs_p.data();
  if (return_type != nullptr) *return_type = "NDArray";
  return 0;
}

MXTPU_API int MXFuncDescribe(FunctionHandle fun, uint32_t* num_use_vars,
                             uint32_t* num_scalars,
                             uint32_t* num_mutate_vars, int* type_mask) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(fun));
  PyObject* res = CallImpl("func_info", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *num_use_vars = static_cast<uint32_t>(
      PyList_Size(PyTuple_GetItem(res, 2)));
  *num_scalars = static_cast<uint32_t>(
      PyLong_AsLong(PyTuple_GetItem(res, 5)));
  *num_mutate_vars = 1;
  *type_mask = 0;
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXFuncInvoke(FunctionHandle fun, NDArrayHandle* use_vars,
                           float* scalar_args, NDArrayHandle* mutate_vars,
                           uint32_t num_use_vars, uint32_t num_scalars,
                           uint32_t num_mutate_vars) {
  Gil gil;
  PyObject* uses = nullptr;
  HandlesToList(num_use_vars, use_vars, &uses);
  PyObject* muts = nullptr;
  HandlesToList(num_mutate_vars, mutate_vars, &muts);
  PyObject* scalars = PyList_New(num_scalars);
  for (uint32_t i = 0; i < num_scalars; ++i) {
    PyList_SetItem(scalars, i, PyFloat_FromDouble(scalar_args[i]));
  }
  PyObject* args = Py_BuildValue("(ONNN)", static_cast<PyObject*>(fun),
                                 uses, scalars, muts);
  PyObject* res = CallImpl("func_invoke", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle* use_vars,
                             float* scalar_args, NDArrayHandle* mutate_vars,
                             uint32_t num_use_vars, uint32_t num_scalars,
                             uint32_t num_mutate_vars, int num_params,
                             char** param_keys, char** param_vals) {
  (void)num_params;
  (void)param_keys;
  (void)param_vals;  // string attrs flow through MXImperativeInvokeByName
  return MXFuncInvoke(fun, use_vars, scalar_args, mutate_vars, num_use_vars,
                      num_scalars, num_mutate_vars);
}

// ---------------------------------------------------------------------------
// RTC (MXRtcCudaModule*: runtime Pallas compilation — rtc.PallasModule)
// ---------------------------------------------------------------------------

typedef void* CudaModuleHandle;
typedef void* CudaKernelHandle;

MXTPU_API int MXRtcCudaModuleCreate(const char* source, int num_options,
                                    const char** options, int num_exports,
                                    const char** exports,
                                    CudaModuleHandle* out) {
  Gil gil;
  PyObject* opts = StrKeysToList(num_options, options);
  PyObject* exps = StrKeysToList(num_exports, exports);
  PyObject* args = Py_BuildValue("(sNN)", source, opts, exps);
  PyObject* res = CallImpl("rtc_module_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXRtcCudaModuleFree(CudaModuleHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXRtcCudaKernelCreate(CudaModuleHandle handle, const char* name,
                                    int num_args, int* is_ndarray,
                                    int* is_const, int* arg_types,
                                    CudaKernelHandle* out) {
  (void)num_args;
  (void)is_ndarray;
  (void)is_const;
  (void)arg_types;  // types come from launch-time JAX tracing
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(handle),
                                 name);
  PyObject* res = CallImpl("rtc_kernel_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXRtcCudaKernelFree(CudaKernelHandle handle) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXRtcCudaKernelCall(CudaKernelHandle handle, int dev_id,
                                  void** ndarray_args, int num_inputs,
                                  int num_outputs) {
  // TPU-native signature: inputs then outputs as NDArray handles (grid /
  // block / shared-mem of the CUDA ABI have no Pallas meaning; the
  // kernel's own grid spec governs).  dev_id ignored: XLA places.
  (void)dev_id;
  Gil gil;
  PyObject* ins = nullptr;
  HandlesToList(static_cast<uint32_t>(num_inputs),
                reinterpret_cast<NDArrayHandle*>(ndarray_args), &ins);
  PyObject* outs = nullptr;
  HandlesToList(static_cast<uint32_t>(num_outputs),
                reinterpret_cast<NDArrayHandle*>(ndarray_args) + num_inputs,
                &outs);
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(handle),
                                 ins, outs);
  PyObject* res = CallImpl("rtc_kernel_call", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// Engine (MXEnginePush*: c_api.h engine block over the C++ host engine)
// ---------------------------------------------------------------------------

typedef void (*EngineSyncFunc)(void* data);

namespace {

struct EngineClosure {
  EngineSyncFunc fn;
  void* data;
};

PyObject* CallCEngineFn(PyObject*, PyObject* args) {
  PyObject* capsule = nullptr;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  auto* cl = static_cast<EngineClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_engine_fn"));
  if (cl == nullptr) return nullptr;
  // release the GIL for the user's C work (it may be long-running IO)
  Py_BEGIN_ALLOW_THREADS
  cl->fn(cl->data);
  Py_END_ALLOW_THREADS
  delete cl;
  Py_RETURN_NONE;
}

PyMethodDef g_call_c_engine_fn_def = {
    "call_c_engine_fn", CallCEngineFn, METH_VARARGS,
    "trampoline into a C engine op"};

int EnginePushImpl(EngineSyncFunc fn, void* data,
                   NDArrayHandle* const_nds, int num_const,
                   NDArrayHandle* mutable_nds, int num_mutable, int wait) {
  Gil gil;
  auto* cl = new EngineClosure{fn, data};
  PyObject* capsule = PyCapsule_New(cl, "mxtpu_engine_fn", nullptr);
  PyObject* tramp = PyCFunction_New(&g_call_c_engine_fn_def, nullptr);
  PyObject* functools = PyImport_ImportModule("functools");
  PyObject* partial = PyObject_GetAttrString(functools, "partial");
  PyObject* bound = PyObject_CallFunctionObjArgs(partial, tramp, capsule,
                                                 nullptr);
  Py_DECREF(functools);
  Py_DECREF(partial);
  Py_DECREF(tramp);
  Py_DECREF(capsule);
  if (bound == nullptr) {
    delete cl;
    return FailFromPython();
  }
  PyObject* cn = nullptr;
  HandlesToList(static_cast<uint32_t>(num_const), const_nds, &cn);
  PyObject* mn = nullptr;
  HandlesToList(static_cast<uint32_t>(num_mutable), mutable_nds, &mn);
  PyObject* args = Py_BuildValue("(NNNi)", bound, cn, mn, wait);
  PyObject* res = CallImpl("engine_push", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXTPU_API int MXEnginePushSyncND(EngineSyncFunc sync_func, void* func_param,
                                 void* deleter_param, void* ctx_handle,
                                 NDArrayHandle* const_nds_handle,
                                 int num_const_nds,
                                 NDArrayHandle* mutable_nds_handle,
                                 int num_mutable_nds) {
  (void)deleter_param;
  (void)ctx_handle;
  return EnginePushImpl(sync_func, func_param, const_nds_handle,
                        num_const_nds, mutable_nds_handle, num_mutable_nds,
                        /*wait=*/1);
}

MXTPU_API int MXEnginePushAsyncND(EngineSyncFunc sync_func, void* func_param,
                                  void* deleter_param, void* ctx_handle,
                                  NDArrayHandle* const_nds_handle,
                                  int num_const_nds,
                                  NDArrayHandle* mutable_nds_handle,
                                  int num_mutable_nds) {
  (void)deleter_param;
  (void)ctx_handle;
  return EnginePushImpl(sync_func, func_param, const_nds_handle,
                        num_const_nds, mutable_nds_handle, num_mutable_nds,
                        /*wait=*/0);
}

MXTPU_API int MXEnginePushSync(EngineSyncFunc sync_func, void* func_param,
                               void* deleter_param, void* ctx_handle,
                               void* const_vars, int num_const,
                               void* mutable_vars, int num_mutable) {
  (void)const_vars;
  (void)num_const;
  (void)mutable_vars;
  (void)num_mutable;  // var-handle form degrades to dep-free execution
  return EnginePushImpl(sync_func, func_param, nullptr, 0, nullptr, 0, 1);
}

MXTPU_API int MXEnginePushAsync(EngineSyncFunc sync_func, void* func_param,
                                void* deleter_param, void* ctx_handle,
                                void* const_vars, int num_const,
                                void* mutable_vars, int num_mutable) {
  (void)const_vars;
  (void)num_const;
  (void)mutable_vars;
  (void)num_mutable;
  return EnginePushImpl(sync_func, func_param, nullptr, 0, nullptr, 0, 0);
}

MXTPU_API int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("engine_wait_for_nd", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// misc device queries
// ---------------------------------------------------------------------------

MXTPU_API int MXGetGPUCount(int* out) {
  *out = 0;  // no CUDA devices in the TPU runtime
  return 0;
}

MXTPU_API int MXGetGPUMemoryInformation64(int dev, uint64_t* free_mem,
                                          uint64_t* total_mem) {
  (void)dev;
  *free_mem = 0;
  *total_mem = 0;  // CUDA query; TPU HBM is managed by XLA
  return 0;
}

// ---------------------------------------------------------------------------
// Symbol tail (MXSymbolGetName/Attr/Copy/Internals/InferType/...)
// ---------------------------------------------------------------------------

namespace {

int SymbolToSymbol(const char* fn, SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl(fn, args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

int SymbolToString(const char* fn, SymbolHandle sym, const char** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl(fn, args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out = g_json_buf.c_str();
  return 0;
}

}  // namespace

MXTPU_API int MXSymbolGetName(SymbolHandle sym, const char** out,
                              int* success) {
  int rc = SymbolToString("symbol_get_name", sym, out);
  if (success != nullptr) *success = (rc == 0 && **out != '\0') ? 1 : 0;
  return rc;
}

MXTPU_API int MXSymbolGetAttr(SymbolHandle sym, const char* key,
                              const char** out, int* success) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym), key);
  PyObject* res = CallImpl("symbol_get_attr", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out = g_json_buf.c_str();
  if (success != nullptr) *success = g_json_buf.empty() ? 0 : 1;
  return 0;
}

MXTPU_API int MXSymbolSetAttr(SymbolHandle sym, const char* key,
                              const char* value) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", static_cast<PyObject*>(sym), key,
                                 value);
  PyObject* res = CallImpl("symbol_set_attr", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXSymbolListAttr(SymbolHandle sym, uint32_t* out_size,
                               const char*** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_list_attr", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  StoreStringList(res, out_size, out);
  Py_DECREF(res);
  *out_size /= 2;  // (key, value) pairs — reference returns pair count
  return 0;
}

MXTPU_API int MXSymbolListAttrShallow(SymbolHandle sym, uint32_t* out_size,
                                      const char*** out) {
  return MXSymbolListAttr(sym, out_size, out);
}

MXTPU_API int MXSymbolCopy(SymbolHandle sym, SymbolHandle* out) {
  return SymbolToSymbol("symbol_copy", sym, out);
}

MXTPU_API int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle* out) {
  return SymbolToSymbol("symbol_get_internals", sym, out);
}

MXTPU_API int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle* out) {
  return SymbolToSymbol("symbol_get_children", sym, out);
}

MXTPU_API int MXSymbolGetOutput(SymbolHandle sym, uint32_t index,
                                SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(sym), index);
  PyObject* res = CallImpl("symbol_get_output", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXSymbolGetNumOutputs(SymbolHandle sym, uint32_t* out) {
  Gil gil;
  int v = 0;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  int rc = CallIntImpl("symbol_get_num_outputs", args, &v);
  *out = static_cast<uint32_t>(v);
  return rc;
}

MXTPU_API int MXSymbolSaveToFile(SymbolHandle sym, const char* fname) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym), fname);
  PyObject* res = CallImpl("symbol_save_file", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* res = CallImpl("symbol_load_file", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXSymbolPrint(SymbolHandle sym, const char** out_str) {
  return SymbolToString("symbol_print", sym, out_str);
}

MXTPU_API int MXSymbolInferType(SymbolHandle sym, uint32_t num_args,
                                const char** keys, const int* arg_type_data,
                                uint32_t* in_type_size,
                                const int** in_type_data,
                                uint32_t* out_type_size,
                                const int** out_type_data,
                                uint32_t* aux_type_size,
                                const int** aux_type_data, int* complete) {
  Gil gil;
  PyObject* k = StrKeysToList(num_args, keys);
  PyObject* codes = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SetItem(codes, i, PyLong_FromLong(arg_type_data[i]));
  }
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym), k,
                                 codes);
  PyObject* res = CallImpl("symbol_infer_type", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  static thread_local std::vector<int> in_t, out_t, aux_t;
  auto fill = [&](PyObject* lst, std::vector<int>* dst) {
    dst->clear();
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i) {
      dst->push_back(static_cast<int>(
          PyLong_AsLong(PyList_GetItem(lst, i))));
    }
  };
  fill(PyTuple_GetItem(res, 0), &in_t);
  fill(PyTuple_GetItem(res, 1), &out_t);
  fill(PyTuple_GetItem(res, 2), &aux_t);
  Py_DECREF(res);
  *in_type_size = static_cast<uint32_t>(in_t.size());
  *in_type_data = in_t.data();
  *out_type_size = static_cast<uint32_t>(out_t.size());
  *out_type_data = out_t.data();
  *aux_type_size = static_cast<uint32_t>(aux_t.size());
  *aux_type_data = aux_t.data();
  bool done = true;
  for (int c : in_t) done = done && c != -1;
  for (int c : out_t) done = done && c != -1;
  for (int c : aux_t) done = done && c != -1;
  if (complete != nullptr) *complete = done ? 1 : 0;
  return 0;
}

// ---------------------------------------------------------------------------
// Quantization / subgraph / kvstore tail / raw-bytes
// ---------------------------------------------------------------------------

MXTPU_API int MXQuantizeSymbol(SymbolHandle sym, SymbolHandle* out,
                               const uint32_t num_excluded,
                               const char** excluded_symbols,
                               const uint32_t num_offline,
                               const char** offline_params,
                               const char* quantized_dtype) {
  (void)num_offline;
  (void)offline_params;  // weights quantize in-graph (quantize_v2)
  (void)quantized_dtype;  // int8 only on the MXU
  Gil gil;
  PyObject* ex = StrKeysToList(num_excluded, excluded_symbols);
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(sym), ex);
  PyObject* res = CallImpl("quantize_symbol", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXGenBackendSubgraph(SymbolHandle sym, const char* backend,
                                   SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym),
                                 backend == nullptr ? "" : backend);
  PyObject* res = CallImpl("gen_backend_subgraph", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXKVStorePushPull(KVStoreHandle kv, uint32_t num,
                                const int* keys, NDArrayHandle* vals,
                                NDArrayHandle* outs, int priority) {
  Gil gil;
  PyObject* k = IntKeysToList(num, keys);
  PyObject* v = nullptr;
  HandlesToList(num, vals, &v);
  PyObject* o = nullptr;
  HandlesToList(num, outs, &o);
  PyObject* args = Py_BuildValue("(ONNNi)", static_cast<PyObject*>(kv), k,
                                 v, o, priority);
  PyObject* res = CallImpl("kvstore_pushpull", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXKVStorePushPullEx(KVStoreHandle kv, uint32_t num,
                                  const char** keys, NDArrayHandle* vals,
                                  NDArrayHandle* outs, int priority) {
  Gil gil;
  PyObject* k = StrKeysToList(num, keys);
  PyObject* v = nullptr;
  HandlesToList(num, vals, &v);
  PyObject* o = nullptr;
  HandlesToList(num, outs, &o);
  PyObject* args = Py_BuildValue("(ONNNi)", static_cast<PyObject*>(kv), k,
                                 v, o, priority);
  PyObject* res = CallImpl("kvstore_pushpull", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXKVStoreSetGradientCompression(KVStoreHandle kv,
                                              uint32_t num_params,
                                              const char** keys,
                                              const char** vals) {
  Gil gil;
  PyObject* k = StrKeysToList(num_params, keys);
  PyObject* v = StrKeysToList(num_params, vals);
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(kv), k, v);
  PyObject* res = CallImpl("kvstore_set_gradient_compression", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t* out_size,
                                    const char** out_buf) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_save_raw_bytes", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  char* b = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(res, &b, &n);
  g_json_buf.assign(b, static_cast<size_t>(n));
  Py_DECREF(res);
  *out_buf = g_json_buf.data();
  *out_size = g_json_buf.size();
  return 0;
}

MXTPU_API int MXNDArrayLoadFromRawBytes(const void* buf, size_t size,
                                        NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(y#)", static_cast<const char*>(buf),
      static_cast<Py_ssize_t>(size));
  PyObject* res = CallImpl("ndarray_load_from_raw_bytes", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

// ---------------------------------------------------------------------------
// NDArray tail: 64-bit / Ex variants, storage type, data access, shared mem,
// sparse aux surface, dlpack (c_api.h NDArray block completion)
// ---------------------------------------------------------------------------

namespace {

thread_local std::vector<int> g_shape_int_buf;
thread_local std::vector<int64_t> g_shape_i64_buf;

// shared int-list marshalling for the shape-returning variants
PyObject* NDArrayShapeList(NDArrayHandle handle) {
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_shape", args);
  Py_DECREF(args);
  return res;
}

}  // namespace

MXTPU_API int MXNDArrayWaitAll() {
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallImpl("engine_wait_all", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayGetShapeEx(NDArrayHandle handle, int* out_dim,
                                  const int** out_pdata) {
  Gil gil;
  PyObject* res = NDArrayShapeList(handle);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_shape_int_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_shape_int_buf[i] = static_cast<int>(PyLong_AsLong(PyList_GetItem(res, i)));
  }
  Py_DECREF(res);
  *out_dim = static_cast<int>(n);
  *out_pdata = g_shape_int_buf.data();
  return 0;
}

MXTPU_API int MXNDArrayGetShape64(NDArrayHandle handle, int* out_dim,
                                  const int64_t** out_pdata) {
  Gil gil;
  PyObject* res = NDArrayShapeList(handle);
  if (res == nullptr) return FailFromPython();
  Py_ssize_t n = PyList_Size(res);
  g_shape_i64_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_shape_i64_buf[i] = PyLong_AsLongLong(PyList_GetItem(res, i));
  }
  Py_DECREF(res);
  *out_dim = static_cast<int>(n);
  *out_pdata = g_shape_i64_buf.data();
  return 0;
}

MXTPU_API int MXNDArrayGetShapeEx64(NDArrayHandle handle, int* out_dim,
                                    const int64_t** out_pdata) {
  return MXNDArrayGetShape64(handle, out_dim, out_pdata);
}

MXTPU_API int MXNDArrayCreateEx64(const int64_t* shape, int ndim, int dev_type,
                                  int dev_id, int delay_alloc, int dtype,
                                  NDArrayHandle* out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* args = Py_BuildValue("(Ni)", shp, dtype);
  PyObject* res = CallImpl("ndarray_create", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayCreateNone(NDArrayHandle* out) {
  // placeholder handle: a 0-element f32 vector (the reference's "none"
  // NDArray is an empty chunk later assigned through MoveTo/CopyFrom)
  const uint32_t shape[1] = {0};
  return MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0, out);
}

MXTPU_API int MXNDArrayReshape64(NDArrayHandle handle, int ndim,
                                 const int64_t* dims, bool reverse,
                                 NDArrayHandle* out) {
  Gil gil;
  PyObject* shape = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyList_SetItem(shape, i, PyLong_FromLongLong(dims[i]));
  }
  PyObject* args = Py_BuildValue("(ONi)", static_cast<PyObject*>(handle),
                                 shape, reverse ? 1 : 0);
  PyObject* res = CallImpl("ndarray_reshape_reverse", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArraySlice64(NDArrayHandle handle, int64_t begin,
                               int64_t end, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OLL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(begin),
                                 static_cast<long long>(end));
  PyObject* res = CallImpl("ndarray_slice", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayAt64(NDArrayHandle handle, int64_t idx,
                            NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(idx));
  PyObject* res = CallImpl("ndarray_at", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayGetStorageType(NDArrayHandle handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_storage_type", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayGetData(NDArrayHandle handle, void** out_pdata) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_data_ptr", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out_pdata = reinterpret_cast<void*>(PyLong_AsSize_t(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayGetGradState(NDArrayHandle handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_get_grad_state", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArraySetGradState(NDArrayHandle handle, int state) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle),
                                 state);
  PyObject* res = CallImpl("ndarray_set_grad_state", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXShallowCopyNDArray(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_shallow_copy", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromNDArray(NDArrayHandle dst,
                                           NDArrayHandle src, int loc) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OOi)", static_cast<PyObject*>(dst),
                                 static_cast<PyObject*>(src), loc);
  PyObject* res = CallImpl("ndarray_sync_copy_from_ndarray", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArraySyncCheckFormat(NDArrayHandle handle, bool full_check) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle),
                                 full_check ? 1 : 0);
  PyObject* res = CallImpl("ndarray_check_format", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayLoadFromBuffer(const void* buf, size_t size,
                                      uint32_t* out_size,
                                      NDArrayHandle** out_arr,
                                      uint32_t* out_name_size,
                                      const char*** out_names) {
  Gil gil;
  PyObject* args = Py_BuildValue("(y#)", static_cast<const char*>(buf),
                                 static_cast<Py_ssize_t>(size));
  PyObject* res = CallImpl("ndarray_load_from_buffer", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  PyObject* arrs = PyTuple_GetItem(res, 0);
  PyObject* names = PyTuple_GetItem(res, 1);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(arrs); ++i) {
    PyObject* a = PyList_GetItem(arrs, i);
    Py_INCREF(a);
    g_handle_store.push_back(a);
  }
  *out_size = static_cast<uint32_t>(g_handle_store.size());
  *out_arr = g_handle_store.data();
  int rc = StoreStringList(names, out_name_size, out_names);
  Py_DECREF(res);
  return rc;
}

// -- sparse surface ---------------------------------------------------------

MXTPU_API int MXNDArrayCreateSparseEx(
    int storage_type, const uint32_t* shape, uint32_t ndim, int dev_type,
    int dev_id, int delay_alloc, int dtype, uint32_t num_aux,
    int* aux_type, uint32_t* aux_ndims, const uint32_t* aux_shape,
    NDArrayHandle* out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  Gil gil;
  PyObject* shp = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i) {
    PyList_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* types = PyList_New(num_aux);
  PyObject* shapes = PyList_New(num_aux);
  uint32_t off = 0;
  for (uint32_t i = 0; i < num_aux; ++i) {
    PyList_SetItem(types, i, PyLong_FromLong(aux_type ? aux_type[i] : 6));
    PyObject* s = PyList_New(aux_ndims[i]);
    for (uint32_t j = 0; j < aux_ndims[i]; ++j) {
      PyList_SetItem(s, j, PyLong_FromUnsignedLong(aux_shape[off + j]));
    }
    off += aux_ndims[i];
    PyList_SetItem(shapes, i, s);
  }
  PyObject* args = Py_BuildValue("(iNiNN)", storage_type, shp, dtype, types,
                                 shapes);
  PyObject* res = CallImpl("ndarray_create_sparse", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayCreateSparseEx64(
    int storage_type, const int64_t* shape, int ndim, int dev_type,
    int dev_id, int delay_alloc, int dtype, uint32_t num_aux,
    int* aux_type, int* aux_ndims, const int64_t* aux_shape,
    NDArrayHandle* out) {
  size_t total = 0;
  for (uint32_t i = 0; i < num_aux; ++i) {
    total += static_cast<size_t>(aux_ndims[i]);
  }
  // the 64-bit variant exists FOR >2^31 dims: refuse to truncate
  for (int i = 0; i < ndim; ++i) {
    if (shape[i] < 0 || shape[i] > UINT32_MAX) {
      return Fail("MXNDArrayCreateSparseEx64: dim " + std::to_string(i) +
                  " = " + std::to_string(shape[i]) +
                  " exceeds the sparse create path's 32-bit dim budget");
    }
  }
  for (size_t i = 0; i < total; ++i) {
    if (aux_shape[i] < 0 || aux_shape[i] > UINT32_MAX) {
      return Fail("MXNDArrayCreateSparseEx64: aux dim exceeds the 32-bit "
                  "dim budget");
    }
  }
  std::vector<uint32_t> shp(shape, shape + ndim);
  std::vector<uint32_t> andims(aux_ndims, aux_ndims + num_aux);
  std::vector<uint32_t> ashape(aux_shape, aux_shape + total);
  return MXNDArrayCreateSparseEx(storage_type, shp.data(),
                                 static_cast<uint32_t>(ndim), dev_type,
                                 dev_id, delay_alloc, dtype, num_aux,
                                 aux_type, andims.data(), ashape.data(), out);
}

MXTPU_API int MXNDArrayGetAuxNDArray(NDArrayHandle handle, uint32_t i,
                                     NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(handle), i);
  PyObject* res = CallImpl("ndarray_aux_ndarray", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayGetAuxNDArray64(NDArrayHandle handle, int64_t i,
                                       NDArrayHandle* out) {
  return MXNDArrayGetAuxNDArray(handle, static_cast<uint32_t>(i), out);
}

MXTPU_API int MXNDArrayGetAuxType(NDArrayHandle handle, uint32_t i,
                                  int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(handle), i);
  PyObject* res = CallImpl("ndarray_aux_type", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayGetAuxType64(NDArrayHandle handle, int64_t i,
                                    int* out) {
  return MXNDArrayGetAuxType(handle, static_cast<uint32_t>(i), out);
}

MXTPU_API int MXNDArrayGetDataNDArray(NDArrayHandle handle,
                                      NDArrayHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_data_ndarray", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

// -- shared-memory transport ------------------------------------------------
// The reference ABI identifies a segment by (shared_pid, shared_id); here
// the pair deterministically derives the POSIX shm name (capi_impl.py
// _shm_name), so any process holding the two ints can reattach — no
// process-local state.

MXTPU_API int MXNDArrayGetSharedMemHandle(NDArrayHandle handle,
                                          int* shared_pid, int* shared_id) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("ndarray_to_shared_mem", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *shared_pid = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 0)));
  *shared_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 1)));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXNDArrayCreateFromSharedMemEx(int shared_pid, int shared_id,
                                             const int* shape, int ndim,
                                             int dtype, NDArrayHandle* out) {
  Gil gil;
  PyObject* shp = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyList_SetItem(shp, i, PyLong_FromLong(shape[i]));
  }
  PyObject* args = Py_BuildValue("(iiNi)", shared_pid, shared_id, shp, dtype);
  PyObject* res = CallImpl("ndarray_from_shared_mem", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                           const uint32_t* shape,
                                           uint32_t ndim, int dtype,
                                           NDArrayHandle* out) {
  std::vector<int> shp(shape, shape + ndim);
  return MXNDArrayCreateFromSharedMemEx(shared_pid, shared_id, shp.data(),
                                        static_cast<int>(ndim), dtype, out);
}

// ---------------------------------------------------------------------------
// Symbol tail: atomic-symbol creation/compose, graph surgery, type partial
// (c_api_symbolic.cc parity block)
// ---------------------------------------------------------------------------

namespace {

// keys/vals -> two PyLists (borrowed into a tuple by the caller)
PyObject* StrList(uint32_t n, const char** strs) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyList_SetItem(lst, i, PyUnicode_FromString(strs[i] ? strs[i] : ""));
  }
  return lst;
}

}  // namespace

MXTPU_API int MXSymbolCreateAtomicSymbol(const char* op_name,
                                         uint32_t num_param,
                                         const char** keys,
                                         const char** vals,
                                         SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(sNN)", op_name, StrList(num_param, keys),
                                 StrList(num_param, vals));
  PyObject* res = CallImpl("symbol_create_atomic", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXSymbolCompose(SymbolHandle sym, const char* name,
                              uint32_t num_args, const char** keys,
                              SymbolHandle* args_handles) {
  Gil gil;
  PyObject* names = PyList_New(num_args);
  PyObject* ins = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(
        (keys != nullptr && keys[i] != nullptr) ? keys[i] : ""));
    PyObject* h = static_cast<PyObject*>(args_handles[i]);
    Py_INCREF(h);
    PyList_SetItem(ins, i, h);
  }
  PyObject* args = Py_BuildValue("(OsNN)", static_cast<PyObject*>(sym),
                                 name ? name : "", names, ins);
  PyObject* res = CallImpl("symbol_compose", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXSymbolCreateGroup(uint32_t num_symbols,
                                  SymbolHandle* symbols, SymbolHandle* out) {
  Gil gil;
  PyObject* lst = PyList_New(num_symbols);
  for (uint32_t i = 0; i < num_symbols; ++i) {
    PyObject* h = static_cast<PyObject*>(symbols[i]);
    Py_INCREF(h);
    PyList_SetItem(lst, i, h);
  }
  PyObject* args = Py_BuildValue("(N)", lst);
  PyObject* res = CallImpl("symbol_create_group", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXSymbolGetAtomicSymbolName(SymbolHandle sym,
                                          const char** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_get_atomic_name", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out = g_json_buf.c_str();
  return 0;
}

MXTPU_API int MXGenAtomicSymbolFromSymbol(SymbolHandle sym,
                                          SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_gen_atomic", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXShallowCopySymbol(SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_shallow_copy", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXSymbolGetInputSymbols(SymbolHandle sym,
                                      SymbolHandle** inputs, int* input_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_get_input_symbols", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    PyObject* h = PyList_GetItem(res, i);
    Py_INCREF(h);
    g_handle_store.push_back(h);
  }
  Py_DECREF(res);
  *inputs = g_handle_store.data();
  *input_size = static_cast<int>(g_handle_store.size());
  return 0;
}

MXTPU_API int MXSymbolCutSubgraph(SymbolHandle sym, SymbolHandle** inputs,
                                  int* input_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_cut_subgraph", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    PyObject* h = PyList_GetItem(res, i);
    Py_INCREF(h);
    g_handle_store.push_back(h);
  }
  Py_DECREF(res);
  *inputs = g_handle_store.data();
  *input_size = static_cast<int>(g_handle_store.size());
  return 0;
}

MXTPU_API int MXSymbolGrad(SymbolHandle sym, uint32_t num_wrt,
                           const char** wrt, SymbolHandle* out) {
  // parity with the reference: c_api_symbolic.cc:910 is LOG(FATAL)
  // "not implemented"; gradients flow through Executor.backward (vjp)
  (void)sym; (void)num_wrt; (void)wrt; (void)out;
  return Fail("MXSymbolGrad: not implemented (reference parity; use "
              "Executor backward)");
}

MXTPU_API int MXSymbolInferTypePartial(SymbolHandle sym, uint32_t num_args,
                                       const char** keys,
                                       const int* arg_type_data,
                                       uint32_t* in_type_size,
                                       const int** in_type_data,
                                       uint32_t* out_type_size,
                                       const int** out_type_data,
                                       uint32_t* aux_type_size,
                                       const int** aux_type_data,
                                       int* complete) {
  Gil gil;
  PyObject* k = StrKeysToList(num_args, keys);
  PyObject* codes = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SetItem(codes, i, PyLong_FromLong(arg_type_data[i]));
  }
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym), k,
                                 codes);
  PyObject* res = CallImpl("symbol_infer_type_partial", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  static thread_local std::vector<int> in_t, out_t, aux_t;
  auto fill = [&](PyObject* lst, std::vector<int>* dst) {
    dst->clear();
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i) {
      dst->push_back(static_cast<int>(
          PyLong_AsLong(PyList_GetItem(lst, i))));
    }
  };
  fill(PyTuple_GetItem(res, 0), &in_t);
  fill(PyTuple_GetItem(res, 1), &out_t);
  fill(PyTuple_GetItem(res, 2), &aux_t);
  Py_DECREF(res);
  *in_type_size = static_cast<uint32_t>(in_t.size());
  *in_type_data = in_t.data();
  *out_type_size = static_cast<uint32_t>(out_t.size());
  *out_type_data = out_t.data();
  *aux_type_size = static_cast<uint32_t>(aux_t.size());
  *aux_type_data = aux_t.data();
  bool done = true;
  for (int c : in_t) done = done && c != -1;
  for (int c : out_t) done = done && c != -1;
  for (int c : aux_t) done = done && c != -1;
  if (complete != nullptr) *complete = done ? 1 : 0;
  return 0;
}

MXTPU_API int MXSymbolRemoveAmpCast(SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallImpl("symbol_remove_amp_cast", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

// ---------------------------------------------------------------------------
// Executor tail: SimpleBind, Reshape, Print, monitor callback, BackwardEx,
// optimized symbol, BindX/BindEX (c_api_executor.cc parity block)
// ---------------------------------------------------------------------------

typedef void* ExecutorHandle;
typedef void(MXExecutorMonitorCallback)(const char*, NDArrayHandle, void*);

namespace {

// unpack the (exe, args, grads, auxs) tuple simple_bind/reshape return;
// allocated handles go to per-thread stores the caller copies out of
thread_local std::vector<NDArrayHandle> g_exec_args, g_exec_grads,
    g_exec_auxs;

int UnpackExecutorTuple(PyObject* res, ExecutorHandle* out,
                        uint32_t* num_in_args, NDArrayHandle** in_args,
                        NDArrayHandle** arg_grads, uint32_t* num_aux,
                        NDArrayHandle** aux_states) {
  PyObject* exe = PyTuple_GetItem(res, 0);
  PyObject* args = PyTuple_GetItem(res, 1);
  PyObject* grads = PyTuple_GetItem(res, 2);
  PyObject* auxs = PyTuple_GetItem(res, 3);
  g_exec_args.clear();
  g_exec_grads.clear();
  g_exec_auxs.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(args); ++i) {
    PyObject* h = PyList_GetItem(args, i);
    Py_INCREF(h);
    g_exec_args.push_back(h);
  }
  for (Py_ssize_t i = 0; i < PyList_Size(grads); ++i) {
    PyObject* h = PyList_GetItem(grads, i);
    if (h == Py_None) {
      g_exec_grads.push_back(nullptr);
    } else {
      Py_INCREF(h);
      g_exec_grads.push_back(h);
    }
  }
  for (Py_ssize_t i = 0; i < PyList_Size(auxs); ++i) {
    PyObject* h = PyList_GetItem(auxs, i);
    Py_INCREF(h);
    g_exec_auxs.push_back(h);
  }
  Py_INCREF(exe);
  *out = exe;
  *num_in_args = static_cast<uint32_t>(g_exec_args.size());
  *in_args = g_exec_args.data();
  *arg_grads = g_exec_grads.data();
  if (num_aux != nullptr) {
    *num_aux = static_cast<uint32_t>(g_exec_auxs.size());
    *aux_states = g_exec_auxs.data();
  }
  return 0;
}

}  // namespace

MXTPU_API int MXExecutorSimpleBindEx(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const uint32_t num_g2c_keys, const char** g2c_keys,
    const int* g2c_dev_types, const int* g2c_dev_ids,
    const uint32_t provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const uint32_t num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const int* provided_arg_shape_data,
    const uint32_t* provided_arg_shape_idx,
    const uint32_t num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const uint32_t num_provided_arg_stypes,
    const char** provided_arg_stype_names, const int* provided_arg_stypes,
    const uint32_t num_shared_arg_names, const char** shared_arg_name_list,
    int* shared_buffer_len, const char** shared_buffer_name_list,
    NDArrayHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list,
    uint32_t* num_in_args, NDArrayHandle** in_args,
    NDArrayHandle** arg_grads, uint32_t* num_aux_states,
    NDArrayHandle** aux_states, ExecutorHandle shared_exec_handle,
    ExecutorHandle* out) {
  // device placement is XLA's; group2ctx / shared buffers are accepted and
  // ignored (single-program compilation has no per-op context assignment)
  (void)dev_type; (void)dev_id; (void)num_g2c_keys; (void)g2c_keys;
  (void)g2c_dev_types; (void)g2c_dev_ids; (void)num_provided_arg_stypes;
  (void)provided_arg_stype_names; (void)provided_arg_stypes;
  (void)num_shared_arg_names; (void)shared_arg_name_list;
  (void)shared_buffer_len; (void)shared_buffer_name_list;
  (void)shared_buffer_handle_list; (void)updated_shared_buffer_name_list;
  (void)updated_shared_buffer_handle_list; (void)shared_exec_handle;
  Gil gil;
  PyObject* shape_keys = PyList_New(num_provided_arg_shapes);
  PyObject* shape_vals = PyList_New(num_provided_arg_shapes);
  for (uint32_t i = 0; i < num_provided_arg_shapes; ++i) {
    PyList_SetItem(shape_keys, i,
                   PyUnicode_FromString(provided_arg_shape_names[i]));
    uint32_t lo = provided_arg_shape_idx[i];
    uint32_t hi = provided_arg_shape_idx[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j) {
      PyList_SetItem(shp, j - lo, PyLong_FromLong(provided_arg_shape_data[j]));
    }
    PyList_SetItem(shape_vals, i, shp);
  }
  PyObject* type_keys = PyList_New(num_provided_arg_dtypes);
  PyObject* type_vals = PyList_New(num_provided_arg_dtypes);
  for (uint32_t i = 0; i < num_provided_arg_dtypes; ++i) {
    PyList_SetItem(type_keys, i,
                   PyUnicode_FromString(provided_arg_dtype_names[i]));
    PyList_SetItem(type_vals, i, PyLong_FromLong(provided_arg_dtypes[i]));
  }
  PyObject* req_names = PyList_New(provided_grad_req_list_len);
  PyObject* req_types = PyList_New(provided_grad_req_list_len);
  for (uint32_t i = 0; i < provided_grad_req_list_len; ++i) {
    const char* n = provided_grad_req_names != nullptr
                        ? provided_grad_req_names[i] : nullptr;
    PyList_SetItem(req_names, i, PyUnicode_FromString(n != nullptr ? n : ""));
    PyList_SetItem(req_types, i, PyUnicode_FromString(
        provided_grad_req_types[i] != nullptr ? provided_grad_req_types[i]
                                              : "write"));
  }
  PyObject* args = Py_BuildValue(
      "(ONNNNNN)", static_cast<PyObject*>(symbol_handle), shape_keys,
      shape_vals, type_keys, type_vals, req_names, req_types);
  PyObject* res = CallImpl("executor_simple_bind", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  int rc = UnpackExecutorTuple(res, out, num_in_args, in_args, arg_grads,
                               num_aux_states, aux_states);
  Py_DECREF(res);
  return rc;
}

MXTPU_API int MXExecutorReshapeEx(int partial_shaping, int allow_up_sizing,
                                  int dev_type, int dev_id,
                                  uint32_t num_map_keys,
                                  const char** map_keys,
                                  const int* map_dev_types,
                                  const int* map_dev_ids,
                                  const uint32_t num_provided_arg_shapes,
                                  const char** provided_arg_shape_names,
                                  const int* provided_arg_shape_data,
                                  const uint32_t* provided_arg_shape_idx,
                                  uint32_t* num_in_args,
                                  NDArrayHandle** in_args,
                                  NDArrayHandle** arg_grads,
                                  uint32_t* num_aux_states,
                                  NDArrayHandle** aux_states,
                                  ExecutorHandle shared_exec,
                                  ExecutorHandle* out) {
  (void)dev_type; (void)dev_id; (void)num_map_keys; (void)map_keys;
  (void)map_dev_types; (void)map_dev_ids;
  Gil gil;
  PyObject* keys = PyList_New(num_provided_arg_shapes);
  PyObject* vals = PyList_New(num_provided_arg_shapes);
  for (uint32_t i = 0; i < num_provided_arg_shapes; ++i) {
    PyList_SetItem(keys, i,
                   PyUnicode_FromString(provided_arg_shape_names[i]));
    uint32_t lo = provided_arg_shape_idx[i];
    uint32_t hi = provided_arg_shape_idx[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j) {
      PyList_SetItem(shp, j - lo, PyLong_FromLong(provided_arg_shape_data[j]));
    }
    PyList_SetItem(vals, i, shp);
  }
  PyObject* args = Py_BuildValue("(ONNii)",
                                 static_cast<PyObject*>(shared_exec), keys,
                                 vals, partial_shaping, allow_up_sizing);
  PyObject* res = CallImpl("executor_reshape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  int rc = UnpackExecutorTuple(res, out, num_in_args, in_args, arg_grads,
                               num_aux_states, aux_states);
  Py_DECREF(res);
  return rc;
}

MXTPU_API int MXExecutorPrint(ExecutorHandle handle, const char** out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("executor_print", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_json_buf = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_str = g_json_buf.c_str();
  return 0;
}

MXTPU_API int MXExecutorGetOptimizedSymbol(ExecutorHandle handle,
                                           SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("executor_symbol", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXExecutorBackwardEx(ExecutorHandle handle, uint32_t len,
                                   NDArrayHandle* head_grads, int is_train) {
  Gil gil;
  PyObject* grads;
  if (len == 0) {
    grads = Py_None;
    Py_INCREF(Py_None);
  } else {
    grads = PyList_New(len);
    for (uint32_t i = 0; i < len; ++i) {
      PyObject* h = static_cast<PyObject*>(head_grads[i]);
      Py_INCREF(h);
      PyList_SetItem(grads, i, h);
    }
  }
  PyObject* args = Py_BuildValue("(ONi)", static_cast<PyObject*>(handle),
                                 grads, is_train);
  PyObject* res = CallImpl("executor_backward_ex", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

namespace {

// C monitor-callback trampoline: wraps the function pointer in a small
// PyCapsule-driven callable the Python executor invokes per output
struct MonitorCtx {
  MXExecutorMonitorCallback* cb;
  void* param;
};

PyObject* MonitorTrampoline(PyObject* self, PyObject* py_args) {
  MonitorCtx* ctx = static_cast<MonitorCtx*>(
      PyCapsule_GetPointer(self, "mxtpu.monitor"));
  const char* name = nullptr;
  PyObject* arr = nullptr;
  if (!PyArg_ParseTuple(py_args, "sO", &name, &arr)) return nullptr;
  // Ownership of the handle transfers to the callee (reference
  // contract: frontends wrap it in NDArray and call MXNDArrayFree,
  // c_api_executor.cc monitor path) — INCREF with no balancing DECREF;
  // the callee's MXNDArrayFree supplies it.
  Py_INCREF(arr);
  ctx->cb(name, arr, ctx->param);
  Py_RETURN_NONE;
}

PyMethodDef g_monitor_def = {"monitor_trampoline", MonitorTrampoline,
                             METH_VARARGS, nullptr};

void MonitorCapsuleDestructor(PyObject* cap) {
  delete static_cast<MonitorCtx*>(
      PyCapsule_GetPointer(cap, "mxtpu.monitor"));
}

}  // namespace

MXTPU_API int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                           MXExecutorMonitorCallback callback,
                                           void* callback_handle) {
  Gil gil;
  MonitorCtx* ctx = new MonitorCtx{callback, callback_handle};
  PyObject* cap = PyCapsule_New(ctx, "mxtpu.monitor",
                                MonitorCapsuleDestructor);
  PyObject* fn = PyCFunction_New(&g_monitor_def, cap);
  Py_DECREF(cap);
  PyObject* args = Py_BuildValue("(ONi)", static_cast<PyObject*>(handle),
                                 fn, 0);
  PyObject* res = CallImpl("executor_set_monitor_callback", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXExecutorSetMonitorCallbackEX(
    ExecutorHandle handle, MXExecutorMonitorCallback callback,
    void* callback_handle, bool monitor_all) {
  Gil gil;
  MonitorCtx* ctx = new MonitorCtx{callback, callback_handle};
  PyObject* cap = PyCapsule_New(ctx, "mxtpu.monitor",
                                MonitorCapsuleDestructor);
  PyObject* fn = PyCFunction_New(&g_monitor_def, cap);
  Py_DECREF(cap);
  PyObject* args = Py_BuildValue("(ONi)", static_cast<PyObject*>(handle),
                                 fn, monitor_all ? 1 : 0);
  PyObject* res = CallImpl("executor_set_monitor_callback", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// Misc runtime tail: numpy-shape mode, bulk size, features, library loading,
// creator-handle imperative invoke, process profiler aliases, AMP/backend
// symbol passes, kvstore sparse pull + env surface
// ---------------------------------------------------------------------------

MXTPU_API int MXIsNumpyShape(int* curr) {
  Gil gil;
  PyObject* res = CallImpl("is_numpy_shape", nullptr);
  if (res == nullptr) return FailFromPython();
  *curr = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXSetIsNumpyShape(int is_np_shape, int* prev) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", is_np_shape);
  PyObject* res = CallImpl("set_is_numpy_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXRandomSeedContext(int seed, int dev_type, int dev_id) {
  (void)dev_type; (void)dev_id;  // one seeded philox stream per process
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  PyObject* res = CallImpl("random_seed", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXEngineSetBulkSize(int bulk_size, int* prev_bulk_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", bulk_size);
  PyObject* res = CallImpl("engine_set_bulk_size", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *prev_bulk_size = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

// mirrors the reference's LibFeature struct (include/mxnet/libinfo.h): the
// caller receives a pointer to an array of {name, enabled}
struct MXTPULibFeature {
  const char* name;
  bool enabled;
};

namespace {
thread_local std::vector<std::string> g_feat_names;
thread_local std::vector<MXTPULibFeature> g_feats;
}  // namespace

MXTPU_API int MXLibInfoFeatures(const MXTPULibFeature** lib_features,
                                size_t* size) {
  Gil gil;
  PyObject* res = CallImpl("libinfo_features", nullptr);
  if (res == nullptr) return FailFromPython();
  PyObject* names = PyTuple_GetItem(res, 0);
  PyObject* flags = PyTuple_GetItem(res, 1);
  Py_ssize_t n = PyList_Size(names);
  g_feat_names.clear();
  g_feats.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_feat_names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(names, i)));
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    g_feats.push_back({g_feat_names[i].c_str(),
                       PyLong_AsLong(PyList_GetItem(flags, i)) != 0});
  }
  Py_DECREF(res);
  *lib_features = g_feats.data();
  *size = static_cast<size_t>(n);
  return 0;
}

MXTPU_API int MXLoadLib(const char* path, unsigned verbose) {
  (void)verbose;
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", path);
  PyObject* res = CallImpl("load_op_library", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXGetGPUMemoryInformation64(int dev, uint64_t* free_mem,
                                          uint64_t* total_mem);

MXTPU_API int MXGetGPUMemoryInformation(int dev, int* free_mem,
                                        int* total_mem) {
  uint64_t f = 0, t = 0;
  int rc = MXGetGPUMemoryInformation64(dev, &f, &t);
  if (rc != 0) return rc;
  *free_mem = static_cast<int>(f >> 20);   // MB, like the reference
  *total_mem = static_cast<int>(t >> 20);
  return 0;
}

// creator-handle imperative invoke: creators ARE interned op-name strings
// (MXSymbolListAtomicSymbolCreators above), so these delegate byte-for-byte
MXTPU_API int MXImperativeInvoke(void* creator, int num_inputs,
                                 NDArrayHandle* inputs, int* num_outputs,
                                 NDArrayHandle** outputs, int num_params,
                                 const char** param_keys,
                                 const char** param_vals) {
  return MXImperativeInvokeByName(static_cast<const char*>(creator),
                                  num_inputs, inputs, num_outputs, outputs,
                                  num_params, param_keys, param_vals);
}

MXTPU_API int MXImperativeInvokeEx(void* creator, int num_inputs,
                                   NDArrayHandle* inputs, int* num_outputs,
                                   NDArrayHandle** outputs, int num_params,
                                   const char** param_keys,
                                   const char** param_vals,
                                   const int** out_stypes) {
  int rc = MXImperativeInvokeByName(static_cast<const char*>(creator),
                                    num_inputs, inputs, num_outputs, outputs,
                                    num_params, param_keys, param_vals);
  if (rc != 0) return rc;
  Gil gil;
  static thread_local std::vector<int> stypes;
  stypes.clear();
  for (int i = 0; i < *num_outputs; ++i) {
    PyObject* args = Py_BuildValue(
        "(O)", static_cast<PyObject*>((*outputs)[i]));
    PyObject* res = CallImpl("ndarray_storage_type", args);
    Py_DECREF(args);
    if (res == nullptr) return FailFromPython();
    stypes.push_back(static_cast<int>(PyLong_AsLong(res)));
    Py_DECREF(res);
  }
  *out_stypes = stypes.data();
  return 0;
}

// symbol creation from a creator handle (reference signature takes the
// creator, not a name; both resolve identically here)
MXTPU_API int MXSymbolCreateAtomicSymbolFromCreator(void* creator,
                                                    uint32_t num_param,
                                                    const char** keys,
                                                    const char** vals,
                                                    SymbolHandle* out) {
  return MXSymbolCreateAtomicSymbol(static_cast<const char*>(creator),
                                    num_param, keys, vals, out);
}

// process-level profiler surface: this runtime has one profiler per
// process, so the process variants alias the per-worker entry points
MXTPU_API int MXSetProfilerConfig(int num_params, const char* const* keys,
                                  const char* const* vals);
MXTPU_API int MXSetProfilerState(int state);
MXTPU_API int MXDumpProfile(int finished);
MXTPU_API int MXProfilePause(int paused);
MXTPU_API int MXAggregateProfileStatsPrint(const char** out_str, int reset);

MXTPU_API int MXSetProcessProfilerConfig(int num_params,
                                         const char* const* keys,
                                         const char* const* vals,
                                         void* kvstore_handle) {
  (void)kvstore_handle;  // dist-server profiling rides the same process
  return MXSetProfilerConfig(num_params, keys, vals);
}

MXTPU_API int MXSetProcessProfilerState(int state, int profile_process,
                                        void* kv_store_handle) {
  (void)profile_process; (void)kv_store_handle;
  return MXSetProfilerState(state);
}

MXTPU_API int MXDumpProcessProfile(int finished, int profile_process,
                                   void* kv_store_handle) {
  (void)profile_process; (void)kv_store_handle;
  return MXDumpProfile(finished);
}

MXTPU_API int MXProcessProfilePause(int paused, int profile_process,
                                    void* kv_store_handle) {
  (void)profile_process; (void)kv_store_handle;
  return MXProfilePause(paused);
}

MXTPU_API int MXAggregateProfileStatsPrintEx(const char** out_str, int reset,
                                             int format, int sort_by,
                                             int ascending) {
  (void)format; (void)sort_by; (void)ascending;  // tabular default
  return MXAggregateProfileStatsPrint(out_str, reset);
}

MXTPU_API int MXReducePrecisionSymbol(SymbolHandle sym, SymbolHandle* out,
                                      uint32_t num_args, const int* arg_types,
                                      uint32_t num_ind_ptr,
                                      const int* ind_ptr,
                                      const int* target_dtype,
                                      const int cast_optional_params,
                                      const uint32_t num_target_dtype_ops,
                                      const uint32_t num_fp32_ops,
                                      const uint32_t num_widest_dtype_ops,
                                      const uint32_t num_conditional_fp32_ops,
                                      const uint32_t num_excluded_symbols,
                                      const uint32_t num_model_params,
                                      const char** target_dtype_ops,
                                      const char** fp32_ops,
                                      const char** widest_dtype_ops,
                                      const char** conditional_fp32_ops,
                                      const char** excluded_symbols,
                                      const char** conditional_param_names,
                                      const char** conditional_param_vals,
                                      const char** model_param_names,
                                      const char** arg_names) {
  (void)num_args; (void)arg_types; (void)num_ind_ptr; (void)ind_ptr;
  (void)cast_optional_params; (void)num_target_dtype_ops; (void)num_fp32_ops;
  (void)num_widest_dtype_ops; (void)num_conditional_fp32_ops;
  (void)num_excluded_symbols; (void)num_model_params; (void)target_dtype_ops;
  (void)fp32_ops; (void)widest_dtype_ops; (void)conditional_fp32_ops;
  (void)excluded_symbols; (void)conditional_param_names;
  (void)conditional_param_vals; (void)model_param_names; (void)arg_names;
  Gil gil;
  const char* dtype = (target_dtype != nullptr && *target_dtype == 2)
                          ? "float16" : "bfloat16";
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym), dtype);
  PyObject* res = CallImpl("amp_reduce_precision_symbol", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXOptimizeForBackend(
    SymbolHandle sym, const char* backend, const int dev_type,
    SymbolHandle* ret_sym, const uint32_t args_len, NDArrayHandle* in_args,
    const uint32_t aux_len, NDArrayHandle* in_aux, const uint32_t num_options,
    const char** keys, const char** vals, int* new_args_cnt,
    NDArrayHandle** new_args_handle, char*** new_arg_names_handle,
    int* new_aux_cnt, NDArrayHandle** new_aux_handle,
    char*** new_aux_names_handle) {
  (void)dev_type; (void)args_len; (void)in_args; (void)aux_len;
  (void)in_aux; (void)num_options; (void)keys; (void)vals;
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym),
                                 backend ? backend : "");
  PyObject* res = CallImpl("symbol_optimize_for", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *ret_sym = res;
  if (new_args_cnt != nullptr) *new_args_cnt = 0;
  if (new_aux_cnt != nullptr) *new_aux_cnt = 0;
  if (new_args_handle != nullptr) *new_args_handle = nullptr;
  if (new_aux_handle != nullptr) *new_aux_handle = nullptr;
  if (new_arg_names_handle != nullptr) *new_arg_names_handle = nullptr;
  if (new_aux_names_handle != nullptr) *new_aux_names_handle = nullptr;
  return 0;
}

typedef void* DataIterCreator;

MXTPU_API int MXDataIterGetIterInfo(DataIterCreator creator,
                                    const char** name,
                                    const char** description,
                                    uint32_t* num_args,
                                    const char*** arg_names,
                                    const char*** arg_types,
                                    const char*** arg_descriptions) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", static_cast<const char*>(creator));
  PyObject* res = CallImpl("data_iter_info", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  g_info_name = PyUnicode_AsUTF8(PyTuple_GetItem(res, 0));
  g_info_desc = PyUnicode_AsUTF8(PyTuple_GetItem(res, 1));
  const char*** outs[3] = {arg_names, arg_types, arg_descriptions};
  uint32_t n = 0;
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GetItem(res, 2 + g);
    Py_ssize_t m = PyList_Size(lst);
    g_info_store[g].clear();
    g_info_ptrs[g].clear();
    for (Py_ssize_t i = 0; i < m; ++i) {
      g_info_store[g].emplace_back(
          PyUnicode_AsUTF8(PyList_GetItem(lst, i)));
    }
    for (auto& s : g_info_store[g]) g_info_ptrs[g].push_back(s.c_str());
    *outs[g] = g_info_ptrs[g].data();
    n = static_cast<uint32_t>(m);
  }
  Py_DECREF(res);
  *name = g_info_name.c_str();
  *description = g_info_desc.c_str();
  *num_args = n;
  return 0;
}

MXTPU_API int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallImpl("autograd_get_symbol", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

// -- kvstore tail -----------------------------------------------------------

MXTPU_API int MXKVStorePullRowSparseEx(KVStoreHandle kv, uint32_t num,
                                       const char** keys,
                                       NDArrayHandle* outs,
                                       NDArrayHandle* row_ids,
                                       int priority) {
  Gil gil;
  PyObject* k = PyList_New(num);
  PyObject* o = PyList_New(num);
  PyObject* r = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i) {
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
    PyObject* oh = static_cast<PyObject*>(outs[i]);
    PyObject* rh = static_cast<PyObject*>(row_ids[i]);
    Py_INCREF(oh);
    Py_INCREF(rh);
    PyList_SetItem(o, i, oh);
    PyList_SetItem(r, i, rh);
  }
  PyObject* args = Py_BuildValue("(ONNNi)", static_cast<PyObject*>(kv), k, o,
                                 r, priority);
  PyObject* res = CallImpl("kvstore_pull_row_sparse", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXKVStorePullRowSparse(KVStoreHandle kv, uint32_t num,
                                     const int* keys, NDArrayHandle* outs,
                                     NDArrayHandle* row_ids, int priority) {
  std::vector<std::string> skeys(num);
  std::vector<const char*> pkeys(num);
  for (uint32_t i = 0; i < num; ++i) {
    skeys[i] = std::to_string(keys[i]);
    pkeys[i] = skeys[i].c_str();
  }
  return MXKVStorePullRowSparseEx(kv, num, pkeys.data(), outs, row_ids,
                                  priority);
}

MXTPU_API int MXInitPSEnv(uint32_t num_vars, const char** keys,
                          const char** vals) {
  // ps-lite env (DMLC_ROLE etc.) — the collective backend reads its own
  // rendezvous env; accept and export so launchers can stay unchanged
  Gil gil;
  for (uint32_t i = 0; i < num_vars; ++i) {
    setenv(keys[i], vals[i], 1);
  }
  return 0;
}

MXTPU_API int MXKVStoreSetBarrierBeforeExit(KVStoreHandle kv,
                                            const int do_barrier) {
  (void)kv; (void)do_barrier;  // exit barrier is implicit in collectives
  return 0;
}

MXTPU_API int MXKVStoreGetNumDeadNode(KVStoreHandle kv, const int node_id,
                                      int* number, const int timeout_sec) {
  (void)kv; (void)node_id; (void)timeout_sec;
  // liveness is the launcher's job (tools/launch.py polling); a reachable
  // store implies zero dead peers in the collective world
  *number = 0;
  return 0;
}

// ---------------------------------------------------------------------------
// Final ABI tail: bind/reshape aliases, Ex/64 infer-shape family, function
// registry by name, kvstore sparse/str-updater, cached-op hook, calib table,
// dlpack, rtc/tvm build-parity errors
// ---------------------------------------------------------------------------

MXTPU_API int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                             uint32_t len, NDArrayHandle* in_args,
                             NDArrayHandle* arg_grad_store,
                             uint32_t* grad_req_type, uint32_t aux_len,
                             NDArrayHandle* aux_states, ExecutorHandle* out);

MXTPU_API int MXExecutorBindX(SymbolHandle sym, int dev_type, int dev_id,
                              uint32_t num_map_keys, const char** map_keys,
                              const int* map_dev_types,
                              const int* map_dev_ids, uint32_t len,
                              NDArrayHandle* in_args,
                              NDArrayHandle* arg_grad_store,
                              uint32_t* grad_req_type, uint32_t aux_len,
                              NDArrayHandle* aux_states,
                              ExecutorHandle* out) {
  // group2ctx maps place op groups on devices; XLA owns placement here
  (void)num_map_keys; (void)map_keys; (void)map_dev_types; (void)map_dev_ids;
  return MXExecutorBind(sym, dev_type, dev_id, len, in_args, arg_grad_store,
                        grad_req_type, aux_len, aux_states, out);
}

MXTPU_API int MXExecutorBindEX(SymbolHandle sym, int dev_type, int dev_id,
                               uint32_t num_map_keys, const char** map_keys,
                               const int* map_dev_types,
                               const int* map_dev_ids, uint32_t len,
                               NDArrayHandle* in_args,
                               NDArrayHandle* arg_grad_store,
                               uint32_t* grad_req_type, uint32_t aux_len,
                               NDArrayHandle* aux_states,
                               ExecutorHandle shared_exec,
                               ExecutorHandle* out) {
  (void)shared_exec;  // memory sharing is XLA buffer assignment's job
  return MXExecutorBindX(sym, dev_type, dev_id, num_map_keys, map_keys,
                         map_dev_types, map_dev_ids, len, in_args,
                         arg_grad_store, grad_req_type, aux_len, aux_states,
                         out);
}

MXTPU_API int MXExecutorSimpleBind(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const uint32_t num_g2c_keys, const char** g2c_keys,
    const int* g2c_dev_types, const int* g2c_dev_ids,
    const uint32_t provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const uint32_t num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const uint32_t* provided_arg_shape_data,
    const uint32_t* provided_arg_shape_idx,
    const uint32_t num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const uint32_t num_provided_arg_stypes,
    const char** provided_arg_stype_names, const int* provided_arg_stypes,
    const uint32_t num_shared_arg_names, const char** shared_arg_name_list,
    int* shared_buffer_len, const char** shared_buffer_name_list,
    NDArrayHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list,
    uint32_t* num_in_args, NDArrayHandle** in_args,
    NDArrayHandle** arg_grads, uint32_t* num_aux_states,
    NDArrayHandle** aux_states, ExecutorHandle shared_exec_handle,
    ExecutorHandle* out) {
  size_t total = num_provided_arg_shapes
                     ? provided_arg_shape_idx[num_provided_arg_shapes] : 0;
  std::vector<int> data(provided_arg_shape_data,
                        provided_arg_shape_data + total);
  return MXExecutorSimpleBindEx(
      symbol_handle, dev_type, dev_id, num_g2c_keys, g2c_keys, g2c_dev_types,
      g2c_dev_ids, provided_grad_req_list_len, provided_grad_req_names,
      provided_grad_req_types, num_provided_arg_shapes,
      provided_arg_shape_names, data.data(), provided_arg_shape_idx,
      num_provided_arg_dtypes, provided_arg_dtype_names, provided_arg_dtypes,
      num_provided_arg_stypes, provided_arg_stype_names, provided_arg_stypes,
      num_shared_arg_names, shared_arg_name_list, shared_buffer_len,
      shared_buffer_name_list, shared_buffer_handle_list,
      updated_shared_buffer_name_list, updated_shared_buffer_handle_list,
      num_in_args, in_args, arg_grads, num_aux_states, aux_states,
      shared_exec_handle, out);
}

MXTPU_API int MXExecutorSimpleBindEx64(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const uint32_t num_g2c_keys, const char** g2c_keys,
    const int* g2c_dev_types, const int* g2c_dev_ids,
    const uint32_t provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const uint32_t num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const int64_t* provided_arg_shape_data,
    const int64_t* provided_arg_shape_idx,
    const uint32_t num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const uint32_t num_provided_arg_stypes,
    const char** provided_arg_stype_names, const int* provided_arg_stypes,
    const uint32_t num_shared_arg_names, const char** shared_arg_name_list,
    int* shared_buffer_len, const char** shared_buffer_name_list,
    NDArrayHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list,
    uint32_t* num_in_args, NDArrayHandle** in_args,
    NDArrayHandle** arg_grads, uint32_t* num_aux_states,
    NDArrayHandle** aux_states, ExecutorHandle shared_exec_handle,
    ExecutorHandle* out) {
  size_t total = num_provided_arg_shapes
                     ? static_cast<size_t>(
                           provided_arg_shape_idx[num_provided_arg_shapes])
                     : 0;
  for (size_t i = 0; i < total; ++i) {
    if (provided_arg_shape_data[i] > INT32_MAX ||
        provided_arg_shape_data[i] < INT32_MIN) {
      return Fail("MXExecutorSimpleBindEx64: shape dim exceeds the bind "
                  "path's 32-bit budget");
    }
  }
  std::vector<int> data(provided_arg_shape_data,
                        provided_arg_shape_data + total);
  std::vector<uint32_t> idx(provided_arg_shape_idx,
                            provided_arg_shape_idx +
                                num_provided_arg_shapes + 1);
  return MXExecutorSimpleBindEx(
      symbol_handle, dev_type, dev_id, num_g2c_keys, g2c_keys, g2c_dev_types,
      g2c_dev_ids, provided_grad_req_list_len, provided_grad_req_names,
      provided_grad_req_types, num_provided_arg_shapes,
      provided_arg_shape_names, data.data(), idx.data(),
      num_provided_arg_dtypes, provided_arg_dtype_names, provided_arg_dtypes,
      num_provided_arg_stypes, provided_arg_stype_names, provided_arg_stypes,
      num_shared_arg_names, shared_arg_name_list, shared_buffer_len,
      shared_buffer_name_list, shared_buffer_handle_list,
      updated_shared_buffer_name_list, updated_shared_buffer_handle_list,
      num_in_args, in_args, arg_grads, num_aux_states, aux_states,
      shared_exec_handle, out);
}

MXTPU_API int MXExecutorReshape(int partial_shaping, int allow_up_sizing,
                                int dev_type, int dev_id,
                                uint32_t num_map_keys, const char** map_keys,
                                const int* map_dev_types,
                                const int* map_dev_ids,
                                const uint32_t num_provided_arg_shapes,
                                const char** provided_arg_shape_names,
                                const uint32_t* provided_arg_shape_data,
                                const uint32_t* provided_arg_shape_idx,
                                uint32_t* num_in_args,
                                NDArrayHandle** in_args,
                                NDArrayHandle** arg_grads,
                                uint32_t* num_aux_states,
                                NDArrayHandle** aux_states,
                                ExecutorHandle shared_exec,
                                ExecutorHandle* out) {
  size_t total = num_provided_arg_shapes
                     ? provided_arg_shape_idx[num_provided_arg_shapes] : 0;
  std::vector<int> data(provided_arg_shape_data,
                        provided_arg_shape_data + total);
  return MXExecutorReshapeEx(partial_shaping, allow_up_sizing, dev_type,
                             dev_id, num_map_keys, map_keys, map_dev_types,
                             map_dev_ids, num_provided_arg_shapes,
                             provided_arg_shape_names, data.data(),
                             provided_arg_shape_idx, num_in_args, in_args,
                             arg_grads, num_aux_states, aux_states,
                             shared_exec, out);
}

// -- Ex/64 infer-shape family ----------------------------------------------
// One generic driver; each ABI variant converts its index/data widths.

namespace {

thread_local std::vector<std::vector<int64_t>> g_isg_shapes[3];
thread_local std::vector<int> g_isg_ndim_int[3];
thread_local std::vector<const int*> g_isg_rows_int[3];
thread_local std::vector<std::vector<int>> g_isg_data_int[3];
thread_local std::vector<const int64_t*> g_isg_rows_i64[3];

int InferShapeGeneric(SymbolHandle sym, uint32_t num_args, const char** keys,
                      const std::vector<std::vector<int64_t>>& in_shapes,
                      int partial, int* complete) {
  Gil gil;
  PyObject* pkeys = PyList_New(num_args);
  PyObject* pshapes = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(keys[i]));
    PyObject* shp = PyList_New(in_shapes[i].size());
    for (size_t d = 0; d < in_shapes[i].size(); ++d) {
      PyList_SetItem(shp, d, PyLong_FromLongLong(in_shapes[i][d]));
    }
    PyList_SetItem(pshapes, i, shp);
  }
  PyObject* args = Py_BuildValue("(ONNi)", static_cast<PyObject*>(sym),
                                 pkeys, pshapes, partial);
  PyObject* res = CallImpl("symbol_infer_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  for (int g = 0; g < 3; ++g) {
    PyObject* group = PyTuple_GetItem(res, g);
    g_isg_shapes[g].clear();
    for (Py_ssize_t i = 0; i < PyList_Size(group); ++i) {
      PyObject* shp = PyList_GetItem(group, i);
      std::vector<int64_t> dims;
      for (Py_ssize_t d = 0; d < PyList_Size(shp); ++d) {
        dims.push_back(PyLong_AsLongLong(PyList_GetItem(shp, d)));
      }
      g_isg_shapes[g].push_back(std::move(dims));
    }
  }
  if (complete != nullptr) {
    *complete = PyObject_IsTrue(PyTuple_GetItem(res, 3));
  }
  Py_DECREF(res);
  return 0;
}

void StoreGroupInt(int g, uint32_t* size, const int** ndim,
                   const int*** data) {
  auto& shapes = g_isg_shapes[g];
  auto& rows = g_isg_rows_int[g];
  auto& store = g_isg_data_int[g];
  store.clear();
  rows.clear();
  g_isg_ndim_int[g].clear();
  for (auto& dims : shapes) {
    std::vector<int> row(dims.begin(), dims.end());
    store.push_back(std::move(row));
    g_isg_ndim_int[g].push_back(static_cast<int>(dims.size()));
  }
  for (auto& row : store) rows.push_back(row.data());
  *size = static_cast<uint32_t>(shapes.size());
  *ndim = g_isg_ndim_int[g].data();
  *data = rows.data();
}

thread_local std::vector<std::vector<int64_t>> g_isg_data_i64[3];

void StoreGroupI64(int g, size_t* size, const int** ndim,
                   const int64_t*** data) {
  auto& shapes = g_isg_shapes[g];
  auto& rows = g_isg_rows_i64[g];
  auto& store = g_isg_data_i64[g];
  store.clear();
  rows.clear();
  g_isg_ndim_int[g].clear();
  for (auto& dims : shapes) {
    store.push_back(dims);
    g_isg_ndim_int[g].push_back(static_cast<int>(dims.size()));
  }
  for (auto& row : store) rows.push_back(row.data());
  *size = shapes.size();
  *ndim = g_isg_ndim_int[g].data();
  *data = rows.data();
}

std::vector<std::vector<int64_t>> PackShapes32(uint32_t num_args,
                                               const uint32_t* ind_ptr,
                                               const int* data) {
  std::vector<std::vector<int64_t>> out(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    for (uint32_t d = ind_ptr[i]; d < ind_ptr[i + 1]; ++d) {
      out[i].push_back(data[d]);
    }
  }
  return out;
}

std::vector<std::vector<int64_t>> PackShapes64(uint32_t num_args,
                                               const int64_t* ind_ptr,
                                               const int64_t* data) {
  std::vector<std::vector<int64_t>> out(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    for (int64_t d = ind_ptr[i]; d < ind_ptr[i + 1]; ++d) {
      out[i].push_back(data[d]);
    }
  }
  return out;
}

}  // namespace

MXTPU_API int MXSymbolInferShapeEx(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const int* arg_shape_data,
    uint32_t* in_shape_size, const int** in_shape_ndim,
    const int*** in_shape_data, uint32_t* out_shape_size,
    const int** out_shape_ndim, const int*** out_shape_data,
    uint32_t* aux_shape_size, const int** aux_shape_ndim,
    const int*** aux_shape_data, int* complete) {
  int rc = InferShapeGeneric(sym, num_args, keys,
                             PackShapes32(num_args, arg_ind_ptr,
                                          arg_shape_data), 0, complete);
  if (rc != 0) return rc;
  StoreGroupInt(0, in_shape_size, in_shape_ndim, in_shape_data);
  StoreGroupInt(1, out_shape_size, out_shape_ndim, out_shape_data);
  StoreGroupInt(2, aux_shape_size, aux_shape_ndim, aux_shape_data);
  return 0;
}

MXTPU_API int MXSymbolInferShapePartialEx(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const int* arg_shape_data,
    uint32_t* in_shape_size, const int** in_shape_ndim,
    const int*** in_shape_data, uint32_t* out_shape_size,
    const int** out_shape_ndim, const int*** out_shape_data,
    uint32_t* aux_shape_size, const int** aux_shape_ndim,
    const int*** aux_shape_data, int* complete) {
  int rc = InferShapeGeneric(sym, num_args, keys,
                             PackShapes32(num_args, arg_ind_ptr,
                                          arg_shape_data), 1, complete);
  if (rc != 0) return rc;
  StoreGroupInt(0, in_shape_size, in_shape_ndim, in_shape_data);
  StoreGroupInt(1, out_shape_size, out_shape_ndim, out_shape_data);
  StoreGroupInt(2, aux_shape_size, aux_shape_ndim, aux_shape_data);
  return 0;
}

MXTPU_API int MXSymbolInferShape64(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const int64_t* arg_ind_ptr, const int64_t* arg_shape_data,
    size_t* in_shape_size, const int** in_shape_ndim,
    const int64_t*** in_shape_data, size_t* out_shape_size,
    const int** out_shape_ndim, const int64_t*** out_shape_data,
    size_t* aux_shape_size, const int** aux_shape_ndim,
    const int64_t*** aux_shape_data, int* complete) {
  int rc = InferShapeGeneric(sym, num_args, keys,
                             PackShapes64(num_args, arg_ind_ptr,
                                          arg_shape_data), 0, complete);
  if (rc != 0) return rc;
  StoreGroupI64(0, in_shape_size, in_shape_ndim, in_shape_data);
  StoreGroupI64(1, out_shape_size, out_shape_ndim, out_shape_data);
  StoreGroupI64(2, aux_shape_size, aux_shape_ndim, aux_shape_data);
  return 0;
}

MXTPU_API int MXSymbolInferShapePartial64(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const int64_t* arg_ind_ptr, const int64_t* arg_shape_data,
    size_t* in_shape_size, const int** in_shape_ndim,
    const int64_t*** in_shape_data, size_t* out_shape_size,
    const int** out_shape_ndim, const int64_t*** out_shape_data,
    size_t* aux_shape_size, const int** aux_shape_ndim,
    const int64_t*** aux_shape_data, int* complete) {
  int rc = InferShapeGeneric(sym, num_args, keys,
                             PackShapes64(num_args, arg_ind_ptr,
                                          arg_shape_data), 1, complete);
  if (rc != 0) return rc;
  StoreGroupI64(0, in_shape_size, in_shape_ndim, in_shape_data);
  StoreGroupI64(1, out_shape_size, out_shape_ndim, out_shape_data);
  StoreGroupI64(2, aux_shape_size, aux_shape_ndim, aux_shape_data);
  return 0;
}

MXTPU_API int MXSymbolInferShapeEx64(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const int64_t* arg_ind_ptr, const int64_t* arg_shape_data,
    size_t* in_shape_size, const int** in_shape_ndim,
    const int64_t*** in_shape_data, size_t* out_shape_size,
    const int** out_shape_ndim, const int64_t*** out_shape_data,
    size_t* aux_shape_size, const int** aux_shape_ndim,
    const int64_t*** aux_shape_data, int* complete) {
  return MXSymbolInferShape64(sym, num_args, keys, arg_ind_ptr,
                              arg_shape_data, in_shape_size, in_shape_ndim,
                              in_shape_data, out_shape_size, out_shape_ndim,
                              out_shape_data, aux_shape_size, aux_shape_ndim,
                              aux_shape_data, complete);
}

MXTPU_API int MXSymbolInferShapePartialEx64(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const int64_t* arg_ind_ptr, const int64_t* arg_shape_data,
    size_t* in_shape_size, const int** in_shape_ndim,
    const int64_t*** in_shape_data, size_t* out_shape_size,
    const int** out_shape_ndim, const int64_t*** out_shape_data,
    size_t* aux_shape_size, const int** aux_shape_ndim,
    const int64_t*** aux_shape_data, int* complete) {
  return MXSymbolInferShapePartial64(sym, num_args, keys, arg_ind_ptr,
                                     arg_shape_data, in_shape_size,
                                     in_shape_ndim, in_shape_data,
                                     out_shape_size, out_shape_ndim,
                                     out_shape_data, aux_shape_size,
                                     aux_shape_ndim, aux_shape_data,
                                     complete);
}

// -- function registry by name / kvstore str-updater / cached-op hook -------

typedef void* FunctionHandle;

namespace {
// process-wide interned function names: unordered_set nodes never move,
// so returned handles stay valid for the process lifetime (function
// handles are long-lived in bindings, unlike the per-call thread-local
// borrow contract the listing entry points use)
std::mutex g_fn_intern_mu;
std::unordered_set<std::string>* FnInternTable() {
  static std::unordered_set<std::string> table;
  return &table;
}
}  // namespace

MXTPU_API int MXGetFunction(const char* name, FunctionHandle* out) {
  Gil gil;
  // validate the name against the registry so unknown names fail here,
  // not at call time
  PyObject* args = Py_BuildValue("(s)", name);
  PyObject* res = CallImpl("get_function_name", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  std::string canonical = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  std::lock_guard<std::mutex> lock(g_fn_intern_mu);
  auto it = FnInternTable()->insert(std::move(canonical)).first;
  *out = const_cast<char*>(it->c_str());
  return 0;
}

typedef void(MXKVStoreStrUpdater)(const char* key, NDArrayHandle recv,
                                  NDArrayHandle local, void* handle);

namespace {

struct UpdaterExClosure {
  MXKVStoreUpdater* fn;
  MXKVStoreStrUpdater* str_fn;
  void* handle;
};

PyObject* CallCUpdaterEx(PyObject*, PyObject* args) {
  PyObject* capsule = nullptr;
  PyObject* key_obj = nullptr;
  PyObject* recv = nullptr;
  PyObject* local = nullptr;
  if (!PyArg_ParseTuple(args, "OOOO", &capsule, &key_obj, &recv, &local)) {
    return nullptr;
  }
  auto* cl = static_cast<UpdaterExClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_updater_ex"));
  if (cl == nullptr) return nullptr;
  // Both handles transfer ownership to the updater (reference
  // contract: the frontend wrapper wraps recv AND local in owning
  // NDArrays that call MXNDArrayFree on destruction); the kvstore's
  // own reference keeps `local` alive after the callee frees its copy.
  if (PyUnicode_Check(key_obj)) {
    // string keys dispatch to the string updater (the API the caller
    // used); numeric conversion is only a fallback when no string
    // updater was registered
    if (cl->str_fn != nullptr) {
      Py_INCREF(recv);
      Py_INCREF(local);
      cl->str_fn(PyUnicode_AsUTF8(key_obj), recv, local, cl->handle);
      Py_RETURN_NONE;
    }
    PyObject* as_int = PyLong_FromUnicodeObject(key_obj, 10);
    if (as_int == nullptr || cl->fn == nullptr) {
      Py_XDECREF(as_int);
      PyErr_SetString(PyExc_TypeError,
                      "no updater registered for string keys");
      return nullptr;
    }
    Py_INCREF(recv);
    Py_INCREF(local);
    cl->fn(static_cast<int>(PyLong_AsLong(as_int)), recv, local,
           cl->handle);
    Py_DECREF(as_int);
  } else {
    if (cl->fn == nullptr) {
      PyErr_SetString(PyExc_TypeError, "no int updater registered");
      return nullptr;
    }
    Py_INCREF(recv);
    Py_INCREF(local);
    cl->fn(static_cast<int>(PyLong_AsLong(key_obj)), recv, local,
           cl->handle);
  }
  Py_RETURN_NONE;
}

PyMethodDef g_call_c_updater_ex_def = {
    "call_c_updater_ex", CallCUpdaterEx, METH_VARARGS,
    "trampoline into a C MXKVStoreUpdater / MXKVStoreStrUpdater pair"};

void FreeUpdaterExCapsule(PyObject* capsule) {
  delete static_cast<UpdaterExClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_updater_ex"));
}

}  // namespace

MXTPU_API int MXKVStoreSetUpdaterEx(KVStoreHandle kv,
                                    MXKVStoreUpdater updater,
                                    MXKVStoreStrUpdater str_updater,
                                    void* updater_handle) {
  Gil gil;
  auto* cl = new UpdaterExClosure{updater, str_updater, updater_handle};
  PyObject* capsule = PyCapsule_New(cl, "mxtpu_updater_ex",
                                    FreeUpdaterExCapsule);
  PyObject* tramp = PyCFunction_New(&g_call_c_updater_ex_def, nullptr);
  PyObject* functools = PyImport_ImportModule("functools");
  PyObject* partial = PyObject_GetAttrString(functools, "partial");
  PyObject* bound = PyObject_CallFunctionObjArgs(partial, tramp, capsule,
                                                 nullptr);
  Py_DECREF(functools);
  Py_DECREF(partial);
  Py_DECREF(tramp);
  Py_DECREF(capsule);
  if (bound == nullptr) return FailFromPython();
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(kv), bound);
  PyObject* res = CallImpl("kvstore_set_updater", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXKVStorePullWithSparseEx(KVStoreHandle kv, uint32_t num,
                                        const char** keys,
                                        NDArrayHandle* vals, int priority,
                                        bool ignore_sparse) {
  Gil gil;
  PyObject* k = PyList_New(num);
  PyObject* o = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i) {
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
    PyObject* oh = static_cast<PyObject*>(vals[i]);
    Py_INCREF(oh);
    PyList_SetItem(o, i, oh);
  }
  PyObject* args = Py_BuildValue("(ONNii)", static_cast<PyObject*>(kv), k, o,
                                 priority, ignore_sparse ? 1 : 0);
  PyObject* res = CallImpl("kvstore_pull_with_sparse", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXKVStorePullWithSparse(KVStoreHandle kv, uint32_t num,
                                      const int* keys, NDArrayHandle* vals,
                                      int priority, bool ignore_sparse) {
  // int keys stay ints (IntKeysToList convention shared with MXKVStorePull)
  Gil gil;
  PyObject* k = PyList_New(num);
  PyObject* o = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i) {
    PyList_SetItem(k, i, PyLong_FromLong(keys[i]));
    PyObject* oh = static_cast<PyObject*>(vals[i]);
    Py_INCREF(oh);
    PyList_SetItem(o, i, oh);
  }
  PyObject* args = Py_BuildValue("(ONNii)", static_cast<PyObject*>(kv), k, o,
                                 priority, ignore_sparse ? 1 : 0);
  PyObject* res = CallImpl("kvstore_pull_with_sparse", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

typedef void(MXTPUCachedOpMonitorCallback)(const char*, const char*,
                                           NDArrayHandle);

namespace {

struct CachedHookClosure {
  MXTPUCachedOpMonitorCallback* fn;
};

PyObject* CallCachedHook(PyObject*, PyObject* args) {
  PyObject* capsule = nullptr;
  const char* name = nullptr;
  const char* opr = nullptr;
  PyObject* arr = nullptr;
  if (!PyArg_ParseTuple(args, "OssO", &capsule, &name, &opr, &arr)) {
    return nullptr;
  }
  auto* cl = static_cast<CachedHookClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_cached_hook"));
  if (cl == nullptr) return nullptr;
  Py_INCREF(arr);  // ownership transfers; callee frees via MXNDArrayFree
  cl->fn(name, opr, arr);
  Py_RETURN_NONE;
}

PyMethodDef g_cached_hook_def = {"call_cached_hook", CallCachedHook,
                                 METH_VARARGS, nullptr};

void FreeCachedHookCapsule(PyObject* capsule) {
  delete static_cast<CachedHookClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_cached_hook"));
}

}  // namespace

MXTPU_API int MXCachedOpRegisterOpHook(NDArrayHandle handle,
                                       MXTPUCachedOpMonitorCallback callback,
                                       bool monitor_all) {
  Gil gil;
  auto* cl = new CachedHookClosure{callback};
  PyObject* capsule = PyCapsule_New(cl, "mxtpu_cached_hook",
                                    FreeCachedHookCapsule);
  PyObject* tramp = PyCFunction_New(&g_cached_hook_def, nullptr);
  PyObject* functools = PyImport_ImportModule("functools");
  PyObject* partial = PyObject_GetAttrString(functools, "partial");
  PyObject* bound = PyObject_CallFunctionObjArgs(partial, tramp, capsule,
                                                 nullptr);
  Py_DECREF(functools);
  Py_DECREF(partial);
  Py_DECREF(tramp);
  Py_DECREF(capsule);
  if (bound == nullptr) return FailFromPython();
  PyObject* args = Py_BuildValue("(ONi)", static_cast<PyObject*>(handle),
                                 bound, monitor_all ? 1 : 0);
  PyObject* res = CallImpl("cached_op_register_hook", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXSetCalibTableToQuantizedSymbol(SymbolHandle qsym,
                                               const uint32_t num_layers,
                                               const char** layer_names,
                                               const float* low_quantiles,
                                               const float* high_quantiles,
                                               SymbolHandle* out) {
  Gil gil;
  PyObject* names = PyList_New(num_layers);
  PyObject* lows = PyList_New(num_layers);
  PyObject* highs = PyList_New(num_layers);
  for (uint32_t i = 0; i < num_layers; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(layer_names[i]));
    PyList_SetItem(lows, i, PyFloat_FromDouble(low_quantiles[i]));
    PyList_SetItem(highs, i, PyFloat_FromDouble(high_quantiles[i]));
  }
  PyObject* args = Py_BuildValue("(ONNN)", static_cast<PyObject*>(qsym),
                                 names, lows, highs);
  PyObject* res = CallImpl("set_calib_table", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

// -- dlpack -----------------------------------------------------------------
// Self-contained DLManagedTensor production/consumption: the exported
// tensor owns a host copy (TPU buffers can't alias host memory; the copy
// IS the honest semantics, exactly like MXNDArraySyncCopyToCPU).

extern "C" {

typedef struct {
  int32_t device_type;  // kDLCPU = 1
  int32_t device_id;
} MXTPUDLDevice;

typedef struct {
  uint8_t code;  // 0=int 1=uint 2=float 4=bfloat 6=bool
  uint8_t bits;
  uint16_t lanes;
} MXTPUDLDataType;

typedef struct {
  void* data;
  MXTPUDLDevice device;
  int32_t ndim;
  MXTPUDLDataType dtype;
  int64_t* shape;
  int64_t* strides;
  uint64_t byte_offset;
} MXTPUDLTensor;

typedef struct MXTPUDLManagedTensor {
  MXTPUDLTensor dl_tensor;
  void* manager_ctx;
  void (*deleter)(struct MXTPUDLManagedTensor* self);
} MXTPUDLManagedTensor;

}  // extern "C"

namespace {

struct DLPackExport {
  MXTPUDLManagedTensor tensor;
  std::vector<char> payload;
  std::vector<int64_t> shape;
};

void DLPackExportDeleter(MXTPUDLManagedTensor* self) {
  delete static_cast<DLPackExport*>(self->manager_ctx);
}

// mshadow dtype code -> (dlpack code, bits)
bool DTypeToDL(int code, uint8_t* dl_code, uint8_t* bits) {
  switch (code) {
    case 0: *dl_code = 2; *bits = 32; return true;   // f32
    case 1: *dl_code = 2; *bits = 64; return true;   // f64
    case 2: *dl_code = 2; *bits = 16; return true;   // f16
    case 3: *dl_code = 1; *bits = 8; return true;    // u8
    case 4: *dl_code = 0; *bits = 32; return true;   // i32
    case 5: *dl_code = 0; *bits = 8; return true;    // i8
    case 6: *dl_code = 0; *bits = 64; return true;   // i64
    case 7: *dl_code = 6; *bits = 8; return true;    // bool
  }
  return false;
}

int DLToDType(uint8_t dl_code, uint8_t bits) {
  if (dl_code == 2 && bits == 32) return 0;
  if (dl_code == 2 && bits == 64) return 1;
  if (dl_code == 2 && bits == 16) return 2;
  if (dl_code == 1 && bits == 8) return 3;
  if (dl_code == 0 && bits == 32) return 4;
  if (dl_code == 0 && bits == 8) return 5;
  if (dl_code == 0 && bits == 64) return 6;
  if (dl_code == 6 && bits == 8) return 7;
  return -1;
}

}  // namespace

MXTPU_API int MXNDArrayToDLPack(NDArrayHandle handle,
                                MXTPUDLManagedTensor** out_dlpack) {
  Gil gil;
  // dtype code + shape + contents
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* dt = CallImpl("ndarray_dtype", args);
  if (dt == nullptr) { Py_DECREF(args); return FailFromPython(); }
  int code = static_cast<int>(PyLong_AsLong(dt));
  Py_DECREF(dt);
  PyObject* shp = CallImpl("ndarray_shape", args);
  if (shp == nullptr) { Py_DECREF(args); return FailFromPython(); }
  PyObject* bytes = CallImpl("ndarray_to_bytes", args);
  Py_DECREF(args);
  if (bytes == nullptr) { Py_DECREF(shp); return FailFromPython(); }

  auto* exp = new DLPackExport();
  for (Py_ssize_t i = 0; i < PyList_Size(shp); ++i) {
    exp->shape.push_back(PyLong_AsLongLong(PyList_GetItem(shp, i)));
  }
  Py_DECREF(shp);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  PyBytes_AsStringAndSize(bytes, &buf, &n);
  exp->payload.assign(buf, buf + n);
  Py_DECREF(bytes);

  uint8_t dl_code = 0, bits = 0;
  if (!DTypeToDL(code, &dl_code, &bits)) {
    delete exp;
    return Fail("dtype code not representable in dlpack");
  }
  exp->tensor.dl_tensor.data = exp->payload.data();
  exp->tensor.dl_tensor.device = {1, 0};  // kDLCPU
  exp->tensor.dl_tensor.ndim = static_cast<int32_t>(exp->shape.size());
  exp->tensor.dl_tensor.dtype = {dl_code, bits, 1};
  exp->tensor.dl_tensor.shape = exp->shape.data();
  exp->tensor.dl_tensor.strides = nullptr;  // compact row-major
  exp->tensor.dl_tensor.byte_offset = 0;
  exp->tensor.manager_ctx = exp;
  exp->tensor.deleter = DLPackExportDeleter;
  *out_dlpack = &exp->tensor;
  return 0;
}

MXTPU_API int MXNDArrayFromDLPackEx(MXTPUDLManagedTensor* dlpack,
                                    const bool transient_handle,
                                    NDArrayHandle* out) {
  (void)transient_handle;
  if (dlpack == nullptr) return Fail("null dlpack tensor");
  MXTPUDLTensor* t = &dlpack->dl_tensor;
  // the data pointer is dereferenced as host memory below; a device
  // tensor (kDLCUDA etc.) would read garbage or fault
  if (t->device.device_type != 1 /* kDLCPU */) {
    return Fail("dlpack import requires a kDLCPU tensor");
  }
  int code = DLToDType(t->dtype.code, t->dtype.bits);
  if (code < 0 || t->dtype.lanes != 1) {
    return Fail("unsupported dlpack dtype");
  }
  // require compact row-major (strides null or matching)
  int64_t elems = 1;
  if (t->strides != nullptr) {
    int64_t expect = 1;
    for (int i = t->ndim - 1; i >= 0; --i) {
      if (t->shape[i] != 1 && t->strides[i] != expect) {
        return Fail("dlpack import requires a compact row-major tensor");
      }
      expect *= t->shape[i];
    }
  }
  for (int i = 0; i < t->ndim; ++i) elems *= t->shape[i];
  size_t nbytes = static_cast<size_t>(elems) * (t->dtype.bits / 8);
  Gil gil;
  PyObject* shp = PyTuple_New(t->ndim);
  for (int i = 0; i < t->ndim; ++i) {
    PyTuple_SetItem(shp, i, PyLong_FromLongLong(t->shape[i]));
  }
  PyObject* args = Py_BuildValue(
      "(Niy#)", shp, code,
      static_cast<const char*>(t->data) + t->byte_offset,
      static_cast<Py_ssize_t>(nbytes));
  PyObject* res = CallImpl("ndarray_from_bytes_dtype", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *out = res;
  return 0;
}

MXTPU_API int MXNDArrayFromDLPack(MXTPUDLManagedTensor* dlpack,
                                  NDArrayHandle* out) {
  return MXNDArrayFromDLPackEx(dlpack, false, out);
}

MXTPU_API int MXNDArrayCallDLPackDeleter(MXTPUDLManagedTensor* dlpack) {
  if (dlpack != nullptr && dlpack->deleter != nullptr) {
    dlpack->deleter(dlpack);
  }
  return 0;
}

// -- rtc / tvm build-parity errors ------------------------------------------
// The reference compiled WITHOUT CUDA / TVM returns an error from these
// entry points (MXNET_USE_CUDA=0 guards, LOG(FATAL) in c_api.cc); this
// runtime's string-kernel path is the Pallas MXRtcCudaKernel* surface.

typedef void* RtcHandle;

MXTPU_API int MXRtcCreate(char* name, uint32_t num_input,
                          uint32_t num_output, char** input_names,
                          char** output_names, NDArrayHandle* inputs,
                          NDArrayHandle* outputs, char* kernel,
                          RtcHandle* out) {
  (void)name; (void)num_input; (void)num_output; (void)input_names;
  (void)output_names; (void)inputs; (void)outputs; (void)kernel; (void)out;
  return Fail("MXRtcCreate: CUDA RTC is not available on the TPU runtime "
              "(use MXRtcCudaKernelCreate's Pallas path)");
}

MXTPU_API int MXRtcPush(RtcHandle handle, uint32_t num_input,
                        uint32_t num_output, NDArrayHandle* inputs,
                        NDArrayHandle* outputs, uint32_t gridDimX,
                        uint32_t gridDimY, uint32_t gridDimZ,
                        uint32_t blockDimX, uint32_t blockDimY,
                        uint32_t blockDimZ) {
  (void)handle; (void)num_input; (void)num_output; (void)inputs;
  (void)outputs; (void)gridDimX; (void)gridDimY; (void)gridDimZ;
  (void)blockDimX; (void)blockDimY; (void)blockDimZ;
  return Fail("MXRtcPush: CUDA RTC is not available on the TPU runtime");
}

MXTPU_API int MXRtcFree(RtcHandle handle) {
  (void)handle;
  return Fail("MXRtcFree: CUDA RTC is not available on the TPU runtime");
}

MXTPU_API int MXLoadTVMConfig(const void* config) {
  (void)config;
  return Fail("MXLoadTVMConfig: built without TVM op support (reference "
              "parity for MXNET_USE_TVM_OP=0; Pallas/rtc.py is the "
              "runtime-kernel path)");
}

MXTPU_API int MXLoadTVMOp(const char* libpath) {
  (void)libpath;
  return Fail("MXLoadTVMOp: built without TVM op support (reference parity "
              "for MXNET_USE_TVM_OP=0)");
}

// -- kvstore server surface -------------------------------------------------

typedef void(MXKVStoreServerController)(int head, const char* body,
                                        void* controller_handle);

namespace {

struct ControllerClosure {
  MXKVStoreServerController* fn;
  void* handle;
};

PyObject* CallCController(PyObject*, PyObject* args) {
  PyObject* capsule = nullptr;
  int head = 0;
  const char* body = nullptr;
  if (!PyArg_ParseTuple(args, "Ois", &capsule, &head, &body)) return nullptr;
  auto* cl = static_cast<ControllerClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_controller"));
  if (cl == nullptr) return nullptr;
  cl->fn(head, body, cl->handle);
  Py_RETURN_NONE;
}

PyMethodDef g_controller_def = {"call_c_controller", CallCController,
                                METH_VARARGS, nullptr};

void FreeControllerCapsule(PyObject* capsule) {
  delete static_cast<ControllerClosure*>(
      PyCapsule_GetPointer(capsule, "mxtpu_controller"));
}

}  // namespace

MXTPU_API int MXKVStoreRunServer(KVStoreHandle kv,
                                 MXKVStoreServerController controller,
                                 void* controller_handle) {
  Gil gil;
  auto* cl = new ControllerClosure{controller, controller_handle};
  PyObject* capsule = PyCapsule_New(cl, "mxtpu_controller",
                                    FreeControllerCapsule);
  PyObject* tramp = PyCFunction_New(&g_controller_def, nullptr);
  PyObject* functools = PyImport_ImportModule("functools");
  PyObject* partial = PyObject_GetAttrString(functools, "partial");
  PyObject* bound = PyObject_CallFunctionObjArgs(partial, tramp, capsule,
                                                 nullptr);
  Py_DECREF(functools);
  Py_DECREF(partial);
  Py_DECREF(tramp);
  Py_DECREF(capsule);
  if (bound == nullptr) return FailFromPython();
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(kv), bound);
  PyObject* res = CallImpl("kvstore_run_server", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXKVStoreSendCommmandToServers(KVStoreHandle kv, int cmd_id,
                                             const char* cmd_body) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Ois)", static_cast<PyObject*>(kv), cmd_id,
                                 cmd_body ? cmd_body : "");
  PyObject* res = CallImpl("kvstore_send_command", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// Custom-op C registration protocol: MXCustomOpRegister /
// MXCustomFunctionRecord (reference include/mxnet/c_api.h:153-217,
// src/operator/custom/custom.cc:70-119, src/c_api/c_api_function.cc:186).
// The reference dispatches these callbacks on dedicated engine threads;
// this runtime's host path is synchronous, so the async callback-thread
// discipline collapses to direct calls.  Ownership of every NDArray
// handle passed to a forward/backward callback TRANSFERS to the callee
// (the reference allocates `new NDArray` per handle in custom.cc
// ForwardEx/BackwardEx and c_api_function.cc Backward); a conforming
// callee frees each handle via MXNDArrayFree after acting on it through
// the same MXNDArray* surface a reference custom-op library uses.
// ---------------------------------------------------------------------------

struct MXTPUCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void** contexts;
};

namespace {

typedef int (*MXTPUCustomOpFBFunc)(int, void**, int*, const int*, const int,
                                   void*);
typedef int (*MXTPUCustomOpDelFunc)(void*);
typedef int (*MXTPUCustomOpListFunc)(char***, void*);
typedef int (*MXTPUCustomOpInferShapeFunc)(int, int*, int**, void*);
typedef int (*MXTPUCustomOpInferTypeFunc)(int, int*, void*);
typedef int (*MXTPUCustomOpBwdDepFunc)(const int*, const int*, const int*,
                                       int*, int**, void*);
typedef int (*MXTPUCustomOpCreateFunc)(const char*, int, unsigned**,
                                       const int*, const int*,
                                       MXTPUCallbackList*, void*);
typedef int (*MXTPUCustomOpPropCreator)(const char*, const int, const char**,
                                        const char**, MXTPUCallbackList*);
typedef int (*MXTPUCustomFunctionBwdFunc)(int, int, void**, const int*,
                                          const int, void*);
typedef int (*MXTPUCustomFunctionDelFunc)(void*);

enum {
  kMXTPUCustomOpDelete,
  kMXTPUCustomOpForward,
  kMXTPUCustomOpBackward
};
enum {
  kMXTPUCustomOpPropDelete,
  kMXTPUCustomOpPropListArguments,
  kMXTPUCustomOpPropListOutputs,
  kMXTPUCustomOpPropListAuxiliaryStates,
  kMXTPUCustomOpPropInferShape,
  kMXTPUCustomOpPropDeclareBackwardDependency,
  kMXTPUCustomOpPropCreateOperator,
  kMXTPUCustomOpPropInferType
};
enum { kMXTPUCustomFunctionBackward, kMXTPUCustomFunctionDelete };

bool CbPresent(const MXTPUCallbackList& cb, int which) {
  return which < cb.num_callbacks && cb.callbacks[which] != nullptr;
}

// owned deep copy of a creator/callee-filled callback list (the caller's
// struct may live on its stack)
MXTPUCallbackList* CopyCbList(const MXTPUCallbackList& src) {
  typedef int (*RawCb)(void);
  auto* dst = new MXTPUCallbackList;
  dst->num_callbacks = src.num_callbacks;
  dst->callbacks = new RawCb[src.num_callbacks];
  dst->contexts = new void*[src.num_callbacks];
  for (int i = 0; i < src.num_callbacks; ++i) {
    dst->callbacks[i] = src.callbacks[i];
    dst->contexts[i] = src.contexts[i];
  }
  return dst;
}

void FreeCbList(MXTPUCallbackList* cb, int del_idx) {
  if (cb == nullptr) return;
  if (CbPresent(*cb, del_idx)) {
    reinterpret_cast<MXTPUCustomOpDelFunc>(cb->callbacks[del_idx])(
        cb->contexts[del_idx]);
  }
  delete[] cb->callbacks;
  delete[] cb->contexts;
  delete cb;
}

void PropCapsuleDel(PyObject* cap) {
  FreeCbList(static_cast<MXTPUCallbackList*>(
                 PyCapsule_GetPointer(cap, "mxtpu_custom_prop")),
             kMXTPUCustomOpPropDelete);
}

void OpCapsuleDel(PyObject* cap) {
  FreeCbList(static_cast<MXTPUCallbackList*>(
                 PyCapsule_GetPointer(cap, "mxtpu_custom_op")),
             kMXTPUCustomOpDelete);
}

void FnCapsuleDel(PyObject* cap) {
  FreeCbList(static_cast<MXTPUCallbackList*>(
                 PyCapsule_GetPointer(cap, "mxtpu_custom_fn")),
             kMXTPUCustomFunctionDelete);
}

MXTPUCallbackList* CapList(PyObject* cap, const char* name) {
  return static_cast<MXTPUCallbackList*>(PyCapsule_GetPointer(cap, name));
}

// trampoline: (creator_capsule, op_type, keys tuple, vals tuple) ->
// prop capsule
PyObject* CCustomPropCreate(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  const char* op_type = nullptr;
  PyObject* keys = nullptr;
  PyObject* vals = nullptr;
  if (!PyArg_ParseTuple(args, "OsOO", &cap, &op_type, &keys, &vals)) {
    return nullptr;
  }
  auto creator = reinterpret_cast<MXTPUCustomOpPropCreator>(
      PyCapsule_GetPointer(cap, "mxtpu_custom_creator"));
  if (creator == nullptr) return nullptr;
  Py_ssize_t n = PyTuple_Size(keys);
  std::vector<const char*> ks(n), vs(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    ks[i] = PyUnicode_AsUTF8(PyTuple_GetItem(keys, i));
    vs[i] = PyUnicode_AsUTF8(PyTuple_GetItem(vals, i));
  }
  MXTPUCallbackList cb{0, nullptr, nullptr};
  if (!creator(op_type, static_cast<int>(n), ks.data(), vs.data(), &cb)) {
    PyErr_Format(PyExc_RuntimeError,
                 "CustomOpPropCreator for %s returned failure", op_type);
    return nullptr;
  }
  return PyCapsule_New(CopyCbList(cb), "mxtpu_custom_prop", PropCapsuleDel);
}

// (prop_capsule, which) -> [str, ...] via a CustomOpListFunc
PyObject* CCustomPropList(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  int which = 0;
  if (!PyArg_ParseTuple(args, "Oi", &cap, &which)) return nullptr;
  auto* cb = CapList(cap, "mxtpu_custom_prop");
  if (cb == nullptr) return nullptr;
  char** names = nullptr;
  if (!CbPresent(*cb, which) ||
      !reinterpret_cast<MXTPUCustomOpListFunc>(cb->callbacks[which])(
          &names, cb->contexts[which])) {
    PyErr_SetString(PyExc_RuntimeError, "custom-op list callback failed");
    return nullptr;
  }
  PyObject* out = PyList_New(0);
  for (int i = 0; names != nullptr && names[i] != nullptr; ++i) {
    PyObject* s = PyUnicode_FromString(names[i]);
    PyList_Append(out, s);
    Py_DECREF(s);
  }
  return out;
}

// (prop_capsule, which) -> bool
PyObject* CCustomPropHas(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  int which = 0;
  if (!PyArg_ParseTuple(args, "Oi", &cap, &which)) return nullptr;
  auto* cb = CapList(cap, "mxtpu_custom_prop");
  if (cb == nullptr) return nullptr;
  return PyBool_FromLong(CbPresent(*cb, which) ? 1 : 0);
}

// (prop_capsule, [[in shapes]], total) -> [[all shapes]] — the callback
// sees ndims/shapes arrays over args+outs+auxs with inputs filled and
// sets the rest to callee-owned storage (custom.cc InferShape contract)
PyObject* CCustomPropInferShape(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  PyObject* in_shapes = nullptr;
  int total = 0;
  if (!PyArg_ParseTuple(args, "OOi", &cap, &in_shapes, &total)) {
    return nullptr;
  }
  auto* cb = CapList(cap, "mxtpu_custom_prop");
  if (cb == nullptr) return nullptr;
  Py_ssize_t n_in = PyList_Size(in_shapes);
  std::vector<std::vector<int>> store(n_in);
  std::vector<int> ndims(total, 0);
  std::vector<int*> shapes(total, nullptr);
  for (Py_ssize_t i = 0; i < n_in; ++i) {
    PyObject* s = PyList_GetItem(in_shapes, i);
    Py_ssize_t d = PyList_Size(s);
    store[i].resize(d);
    for (Py_ssize_t j = 0; j < d; ++j) {
      store[i][j] =
          static_cast<int>(PyLong_AsLong(PyList_GetItem(s, j)));
    }
    ndims[i] = static_cast<int>(d);
    shapes[i] = store[i].data();
  }
  if (!CbPresent(*cb, kMXTPUCustomOpPropInferShape) ||
      !reinterpret_cast<MXTPUCustomOpInferShapeFunc>(
          cb->callbacks[kMXTPUCustomOpPropInferShape])(
          total, ndims.data(), shapes.data(),
          cb->contexts[kMXTPUCustomOpPropInferShape])) {
    PyErr_SetString(PyExc_RuntimeError,
                    "custom-op infer_shape callback failed");
    return nullptr;
  }
  PyObject* out = PyList_New(total);
  for (int i = 0; i < total; ++i) {
    PyObject* s = PyList_New(ndims[i]);
    for (int j = 0; j < ndims[i]; ++j) {
      PyList_SetItem(s, j, PyLong_FromLong(
          shapes[i] != nullptr ? shapes[i][j] : 0));
    }
    PyList_SetItem(out, i, s);
  }
  return out;
}

// (prop_capsule, [in dtype codes], total) -> [all dtype codes]
PyObject* CCustomPropInferType(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  PyObject* in_types = nullptr;
  int total = 0;
  if (!PyArg_ParseTuple(args, "OOi", &cap, &in_types, &total)) {
    return nullptr;
  }
  auto* cb = CapList(cap, "mxtpu_custom_prop");
  if (cb == nullptr) return nullptr;
  std::vector<int> types(total, -1);
  Py_ssize_t n_in = PyList_Size(in_types);
  for (Py_ssize_t i = 0; i < n_in; ++i) {
    types[i] = static_cast<int>(PyLong_AsLong(PyList_GetItem(in_types, i)));
  }
  if (!CbPresent(*cb, kMXTPUCustomOpPropInferType) ||
      !reinterpret_cast<MXTPUCustomOpInferTypeFunc>(
          cb->callbacks[kMXTPUCustomOpPropInferType])(
          total, types.data(), cb->contexts[kMXTPUCustomOpPropInferType])) {
    PyErr_SetString(PyExc_RuntimeError,
                    "custom-op infer_type callback failed");
    return nullptr;
  }
  PyObject* out = PyList_New(total);
  for (int i = 0; i < total; ++i) {
    PyList_SetItem(out, i, PyLong_FromLong(types[i]));
  }
  return out;
}

// (prop_capsule, [out_grad ids], [in_data ids], [out_data ids]) -> [deps]
PyObject* CCustomPropBwdDep(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  PyObject* og = nullptr;
  PyObject* idata = nullptr;
  PyObject* odata = nullptr;
  if (!PyArg_ParseTuple(args, "OOOO", &cap, &og, &idata, &odata)) {
    return nullptr;
  }
  auto* cb = CapList(cap, "mxtpu_custom_prop");
  if (cb == nullptr) return nullptr;
  auto to_vec = [](PyObject* l) {
    std::vector<int> v(PyList_Size(l));
    for (size_t i = 0; i < v.size(); ++i) {
      v[i] = static_cast<int>(
          PyLong_AsLong(PyList_GetItem(l, static_cast<Py_ssize_t>(i))));
    }
    return v;
  };
  std::vector<int> ogv = to_vec(og), iv = to_vec(idata), ov = to_vec(odata);
  int num_deps = 0;
  int* rdeps = nullptr;
  if (!CbPresent(*cb, kMXTPUCustomOpPropDeclareBackwardDependency) ||
      !reinterpret_cast<MXTPUCustomOpBwdDepFunc>(
          cb->callbacks[kMXTPUCustomOpPropDeclareBackwardDependency])(
          ogv.data(), iv.data(), ov.data(), &num_deps, &rdeps,
          cb->contexts[kMXTPUCustomOpPropDeclareBackwardDependency])) {
    PyErr_SetString(PyExc_RuntimeError,
                    "custom-op declare_backward_dependency failed");
    return nullptr;
  }
  PyObject* out = PyList_New(num_deps);
  for (int i = 0; i < num_deps; ++i) {
    PyList_SetItem(out, i, PyLong_FromLong(rdeps[i]));
  }
  return out;
}

// (prop_capsule, ctx_str, [[in shapes]], [in dtypes]) -> op capsule
PyObject* CCustomPropCreateOperator(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  const char* ctx = nullptr;
  PyObject* shps = nullptr;
  PyObject* dts = nullptr;
  if (!PyArg_ParseTuple(args, "OsOO", &cap, &ctx, &shps, &dts)) {
    return nullptr;
  }
  auto* cb = CapList(cap, "mxtpu_custom_prop");
  if (cb == nullptr) return nullptr;
  Py_ssize_t n = PyList_Size(shps);
  std::vector<std::vector<unsigned>> store(n);
  std::vector<unsigned*> shapes(n);
  std::vector<int> ndims(n), dtypes(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* s = PyList_GetItem(shps, i);
    Py_ssize_t d = PyList_Size(s);
    store[i].resize(d);
    for (Py_ssize_t j = 0; j < d; ++j) {
      store[i][j] = static_cast<unsigned>(
          PyLong_AsUnsignedLong(PyList_GetItem(s, j)));
    }
    shapes[i] = store[i].data();
    ndims[i] = static_cast<int>(d);
    dtypes[i] = static_cast<int>(PyLong_AsLong(PyList_GetItem(dts, i)));
  }
  MXTPUCallbackList op{0, nullptr, nullptr};
  if (!CbPresent(*cb, kMXTPUCustomOpPropCreateOperator) ||
      !reinterpret_cast<MXTPUCustomOpCreateFunc>(
          cb->callbacks[kMXTPUCustomOpPropCreateOperator])(
          ctx, static_cast<int>(n), shapes.data(), ndims.data(),
          dtypes.data(), &op,
          cb->contexts[kMXTPUCustomOpPropCreateOperator])) {
    PyErr_SetString(PyExc_RuntimeError,
                    "custom-op create_operator callback failed");
    return nullptr;
  }
  return PyCapsule_New(CopyCbList(op), "mxtpu_custom_op", OpCapsuleDel);
}

// (op_capsule, which, [handles], [tags], [reqs], is_train) — the
// forward/backward CustomOpFBFunc call.  Ownership of each handle
// transfers to the callee (reference custom.cc ForwardEx/BackwardEx
// allocate per-callback NDArrays the callee frees via MXNDArrayFree),
// so every handle is INCREF'd before the call; a callee that never
// frees leaks the ref, exactly as it would leak the reference's
// `new NDArray`.
PyObject* CCustomOpCall(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  int which = 0;
  PyObject* handles = nullptr;
  PyObject* tags = nullptr;
  PyObject* reqs = nullptr;
  int is_train = 0;
  if (!PyArg_ParseTuple(args, "OiOOOi", &cap, &which, &handles, &tags,
                        &reqs, &is_train)) {
    return nullptr;
  }
  auto* cb = CapList(cap, "mxtpu_custom_op");
  if (cb == nullptr) return nullptr;
  Py_ssize_t n = PyList_Size(handles);
  std::vector<void*> ptrs(n);
  std::vector<int> tagv(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* h = PyList_GetItem(handles, i);
    Py_INCREF(h);  // ownership transfers; callee frees via MXNDArrayFree
    ptrs[i] = h;
    tagv[i] = static_cast<int>(PyLong_AsLong(PyList_GetItem(tags, i)));
  }
  Py_ssize_t nr = PyList_Size(reqs);
  std::vector<int> reqv(nr);
  for (Py_ssize_t i = 0; i < nr; ++i) {
    reqv[i] = static_cast<int>(PyLong_AsLong(PyList_GetItem(reqs, i)));
  }
  if (!CbPresent(*cb, which) ||
      !reinterpret_cast<MXTPUCustomOpFBFunc>(cb->callbacks[which])(
          static_cast<int>(n), ptrs.data(), tagv.data(), reqv.data(),
          is_train, cb->contexts[which])) {
    PyErr_SetString(PyExc_RuntimeError,
                    "custom-op forward/backward callback failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

// (fn_capsule, num_ograds, num_igrads, [handles], [reqs], is_train) —
// handle ownership transfers to the callee exactly as in CCustomOpCall
// (reference c_api_function.cc Backward allocates per-call NDArrays)
PyObject* CCustomFunctionCall(PyObject*, PyObject* args) {
  PyObject* cap = nullptr;
  int n_og = 0;
  int n_ig = 0;
  PyObject* handles = nullptr;
  PyObject* reqs = nullptr;
  int is_train = 0;
  if (!PyArg_ParseTuple(args, "OiiOOi", &cap, &n_og, &n_ig, &handles,
                        &reqs, &is_train)) {
    return nullptr;
  }
  auto* cb = CapList(cap, "mxtpu_custom_fn");
  if (cb == nullptr) return nullptr;
  Py_ssize_t n = PyList_Size(handles);
  std::vector<void*> ptrs(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* h = PyList_GetItem(handles, i);
    Py_INCREF(h);  // ownership transfers; callee frees via MXNDArrayFree
    ptrs[i] = h;
  }
  Py_ssize_t nr = PyList_Size(reqs);
  std::vector<int> reqv(nr);
  for (Py_ssize_t i = 0; i < nr; ++i) {
    reqv[i] = static_cast<int>(PyLong_AsLong(PyList_GetItem(reqs, i)));
  }
  if (!CbPresent(*cb, kMXTPUCustomFunctionBackward) ||
      !reinterpret_cast<MXTPUCustomFunctionBwdFunc>(
          cb->callbacks[kMXTPUCustomFunctionBackward])(
          n_og, n_ig, ptrs.data(), reqv.data(), is_train,
          cb->contexts[kMXTPUCustomFunctionBackward])) {
    PyErr_SetString(PyExc_RuntimeError,
                    "custom-function backward callback failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyMethodDef g_custom_defs[] = {
    {"c_custom_prop_create", CCustomPropCreate, METH_VARARGS, nullptr},
    {"c_custom_prop_list", CCustomPropList, METH_VARARGS, nullptr},
    {"c_custom_prop_has", CCustomPropHas, METH_VARARGS, nullptr},
    {"c_custom_prop_infer_shape", CCustomPropInferShape, METH_VARARGS,
     nullptr},
    {"c_custom_prop_infer_type", CCustomPropInferType, METH_VARARGS,
     nullptr},
    {"c_custom_prop_bwd_dep", CCustomPropBwdDep, METH_VARARGS, nullptr},
    {"c_custom_prop_create_operator", CCustomPropCreateOperator,
     METH_VARARGS, nullptr},
    {"c_custom_op_call", CCustomOpCall, METH_VARARGS, nullptr},
    {"c_custom_function_call", CCustomFunctionCall, METH_VARARGS, nullptr},
};

PyObject* CustomTrampolineDict() {
  PyObject* d = PyDict_New();
  for (auto& def : g_custom_defs) {
    PyObject* f = PyCFunction_New(&def, nullptr);
    PyDict_SetItemString(d, def.ml_name, f);
    Py_DECREF(f);
  }
  return d;
}

}  // namespace

MXTPU_API int MXCustomOpRegister(const char* op_type,
                                 MXTPUCustomOpPropCreator creator) {
  Gil gil;
  PyObject* cap = PyCapsule_New(reinterpret_cast<void*>(creator),
                                "mxtpu_custom_creator", nullptr);
  PyObject* args = Py_BuildValue("(sNN)", op_type, cap,
                                 CustomTrampolineDict());
  PyObject* res = CallImpl("custom_op_register_c", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXCustomFunctionRecord(int num_inputs, NDArrayHandle* inputs,
                                     int num_outputs, NDArrayHandle* outputs,
                                     MXTPUCallbackList* callbacks) {
  Gil gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* h = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(h);
    PyList_SetItem(ins, i, h);
  }
  PyObject* outs = PyList_New(num_outputs);
  for (int i = 0; i < num_outputs; ++i) {
    PyObject* h = static_cast<PyObject*>(outputs[i]);
    Py_INCREF(h);
    PyList_SetItem(outs, i, h);
  }
  PyObject* cap = PyCapsule_New(CopyCbList(*callbacks), "mxtpu_custom_fn",
                                FnCapsuleDel);
  PyObject* tramp = nullptr;
  for (auto& def : g_custom_defs) {
    if (std::string(def.ml_name) == "c_custom_function_call") {
      tramp = PyCFunction_New(&def, nullptr);
    }
  }
  PyObject* args = Py_BuildValue("(NNNN)", ins, outs, cap, tramp);
  PyObject* res = CallImpl("custom_function_record", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

// ---------------------------------------------------------------------------
// Test hooks (include/mxnet/c_api_test.h): op-name-driven partitioning
// ---------------------------------------------------------------------------

MXTPU_API int MXBuildSubgraphByOpNames(SymbolHandle sym,
                                       const char* prop_name,
                                       const uint32_t num_ops,
                                       const char** op_names,
                                       SymbolHandle* ret) {
  Gil gil;
  PyObject* names = PyList_New(num_ops);
  for (uint32_t i = 0; i < num_ops; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(op_names[i]));
  }
  PyObject* args = Py_BuildValue("(OsN)", static_cast<PyObject*>(sym),
                                 prop_name, names);
  PyObject* res = CallImpl("build_subgraph_by_op_names", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  *ret = res;
  return 0;
}

MXTPU_API int MXSetSubgraphPropertyOpNames(const char* prop_name,
                                           const uint32_t num_ops,
                                           const char** op_names) {
  Gil gil;
  PyObject* names = PyList_New(num_ops);
  for (uint32_t i = 0; i < num_ops; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(op_names[i]));
  }
  PyObject* args = Py_BuildValue("(sN)", prop_name, names);
  PyObject* res = CallImpl("set_subgraph_property_op_names", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXRemoveSubgraphPropertyOpNames(const char* prop_name) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", prop_name);
  PyObject* res = CallImpl("remove_subgraph_property_op_names", args);
  Py_DECREF(args);
  if (res == nullptr) return FailFromPython();
  Py_DECREF(res);
  return 0;
}
