// Public C header for the MXNet-compatible ABI exported by
// src/native/libmxtpu_capi.so.
//
// Reference contract: include/mxnet/c_api.h (242 MXNET_DLL functions) and
// include/mxnet/c_predict_api.h.  This header declares the implemented
// subset; semantics follow the reference signatures (CSR-style shape
// marshalling, thread-local return buffers valid until the next call on the
// same thread, MXGetLastError after any nonzero return).
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>
#include <stdbool.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* PredictorHandle;
typedef void* AtomicSymbolCreator;

/* error / version ------------------------------------------------------- */
const char* MXGetLastError(void);
int MXGetVersion(int* out);

/* NDArray --------------------------------------------------------------- */
int MXNDArrayCreate(const uint32_t* shape, uint32_t ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out);
int MXNDArrayCreateEx(const uint32_t* shape, uint32_t ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             uint64_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, uint64_t size);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, uint32_t* out_dim,
                      const uint32_t** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out);
int MXNDArraySave(const char* fname, uint32_t num_args, NDArrayHandle* args,
                  const char** keys);
int MXNDArrayLoad(const char* fname, uint32_t* out_size,
                  NDArrayHandle** out_arr, uint32_t* out_name_size,
                  const char*** out_names);

/* ops ------------------------------------------------------------------- */
int MXListAllOpNames(uint32_t* out_size, const char*** out_array);
int MXImperativeInvokeByName(const char* op_name, int num_inputs,
                             NDArrayHandle* inputs, int* num_outputs,
                             NDArrayHandle** outputs, int num_params,
                             const char** param_keys,
                             const char** param_vals);

/* Symbol ---------------------------------------------------------------- */
int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
int MXSymbolFree(SymbolHandle sym);
int MXSymbolListArguments(SymbolHandle sym, uint32_t* out_size,
                          const char*** out_array);
int MXSymbolListOutputs(SymbolHandle sym, uint32_t* out_size,
                        const char*** out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, uint32_t* out_size,
                                const char*** out_array);
int MXSymbolCreateVariable(const char* name, SymbolHandle* out);
/* Op reflection — the surface language bindings code-gen wrappers from.
 * Creator handles are interned op-name strings. */
int MXSymbolListAtomicSymbolCreators(uint32_t* out_size,
                                     AtomicSymbolCreator** out);
int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char** name, const char** description,
                                uint32_t* num_args, const char*** arg_names,
                                const char*** arg_types,
                                const char*** arg_descriptions);
/* One-shot CreateAtomicSymbol+Compose: op node over named/positional input
 * symbols.  input_keys may be NULL (all positional); entries may be NULL. */
int MXSymbolCreateFromOp(const char* op_name, uint32_t num_params,
                         const char** param_keys, const char** param_vals,
                         uint32_t num_inputs, const char** input_keys,
                         SymbolHandle* inputs, const char* name,
                         SymbolHandle* out);
int MXSymbolInferShape(SymbolHandle sym, uint32_t num_args, const char** keys,
                       const uint32_t* arg_ind_ptr,
                       const uint32_t* arg_shape_data,
                       uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
                       const uint32_t*** in_shape_data,
                       uint32_t* out_shape_size,
                       const uint32_t** out_shape_ndim,
                       const uint32_t*** out_shape_data,
                       uint32_t* aux_shape_size,
                       const uint32_t** aux_shape_ndim,
                       const uint32_t*** aux_shape_data, int* complete);
int MXSymbolInferShapePartial(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
    const uint32_t*** in_shape_data, uint32_t* out_shape_size,
    const uint32_t** out_shape_ndim, const uint32_t*** out_shape_data,
    uint32_t* aux_shape_size, const uint32_t** aux_shape_ndim,
    const uint32_t*** aux_shape_data, int* complete);

/* Executor -------------------------------------------------------------- */
/* grad_req_type codes follow OpReqType: 0 null, 1 write, 2 inplace-write,
 * 3 add.  in_args/aux_states arrive in list_arguments /
 * list_auxiliary_states order; arg_grad_store entries may be NULL. */
int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id, uint32_t len,
                   NDArrayHandle* in_args, NDArrayHandle* arg_grad_store,
                   uint32_t* grad_req_type, uint32_t aux_len,
                   NDArrayHandle* aux_states, ExecutorHandle* out);
int MXExecutorForward(ExecutorHandle h, int is_train);
int MXExecutorOutputs(ExecutorHandle h, uint32_t* out_size,
                      NDArrayHandle** out);
int MXExecutorBackward(ExecutorHandle h, uint32_t len,
                       NDArrayHandle* head_grads);
int MXExecutorFree(ExecutorHandle h);

/* Predict API (c_predict_api.h) ----------------------------------------- */
int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out);
int MXPredSetInput(PredictorHandle h, const char* key, const float* data,
                   uint32_t size);
int MXPredForward(PredictorHandle h);
int MXPredGetOutputShape(PredictorHandle h, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim);
int MXPredGetOutput(PredictorHandle h, uint32_t index, float* data,
                    uint32_t size);
int MXPredFree(PredictorHandle h);


/* Autograd (c_api.h MXAutograd* block) ---------------------------------- */
int MXAutogradSetIsRecording(int is_recording, int* prev);
int MXAutogradSetIsTraining(int is_training, int* prev);
int MXAutogradIsRecording(bool* curr);
int MXAutogradIsTraining(bool* curr);
int MXAutogradMarkVariables(uint32_t num_var, NDArrayHandle* var_handles,
                            uint32_t* reqs_array,
                            NDArrayHandle* grad_handles);
int MXAutogradBackward(uint32_t num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph);
int MXAutogradBackwardEx(uint32_t num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles,
                         uint32_t num_variables, NDArrayHandle* var_handles,
                         int retain_graph, int create_graph, int is_train,
                         NDArrayHandle** grad_handles, int** grad_stypes);
int MXAutogradComputeGradient(uint32_t num_output,
                              NDArrayHandle* output_handles);
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out);
int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle* out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, int* dims,
                     NDArrayHandle* out);
int MXNDArraySlice(NDArrayHandle handle, uint32_t begin, uint32_t end,
                   NDArrayHandle* out);
int MXNDArrayAt(NDArrayHandle handle, uint32_t idx, NDArrayHandle* out);
int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id);

/* KVStore (c_api.h MXKVStore* block) ------------------------------------ */
typedef void* KVStoreHandle;
typedef void(MXKVStoreUpdater)(int key, NDArrayHandle recv,
                               NDArrayHandle local, void* handle);
int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle kv, uint32_t num, const int* keys,
                  NDArrayHandle* vals);
int MXKVStoreInitEx(KVStoreHandle kv, uint32_t num, const char** keys,
                    NDArrayHandle* vals);
int MXKVStorePush(KVStoreHandle kv, uint32_t num, const int* keys,
                  NDArrayHandle* vals, int priority);
int MXKVStorePushEx(KVStoreHandle kv, uint32_t num, const char** keys,
                    NDArrayHandle* vals, int priority);
int MXKVStorePull(KVStoreHandle kv, uint32_t num, const int* keys,
                  NDArrayHandle* vals, int priority);
int MXKVStorePullEx(KVStoreHandle kv, uint32_t num, const char** keys,
                    NDArrayHandle* vals, int priority);
int MXKVStoreGetType(KVStoreHandle kv, const char** type);
int MXKVStoreGetRank(KVStoreHandle kv, int* rank);
int MXKVStoreGetGroupSize(KVStoreHandle kv, int* size);
int MXKVStoreBarrier(KVStoreHandle kv);
int MXKVStoreIsWorkerNode(int* ret);
int MXKVStoreIsServerNode(int* ret);
int MXKVStoreIsSchedulerNode(int* ret);
int MXKVStoreSetUpdater(KVStoreHandle kv, MXKVStoreUpdater updater,
                        void* updater_handle);

/* DataIter (c_api.h MXDataIter* block) ---------------------------------- */
typedef void* DataIterHandle;
int MXListDataIters(uint32_t* out_size, const char*** out_array);
int MXDataIterCreateIter(const char* name, uint32_t num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out);
int MXDataIterFree(DataIterHandle handle);
int MXDataIterNext(DataIterHandle handle, int* out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetPadNum(DataIterHandle handle, int* pad);
int MXDataIterGetIndex(DataIterHandle handle, uint64_t** out_index,
                       uint64_t* out_size);

/* RecordIO (c_api.h MXRecordIO* block) ---------------------------------- */
typedef void* RecordIOHandle;
int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out);
int MXRecordIOWriterFree(RecordIOHandle handle);
int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char* buf,
                                size_t size);
int MXRecordIOWriterTell(RecordIOHandle handle, size_t* pos);
int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out);
int MXRecordIOReaderFree(RecordIOHandle handle);
int MXRecordIOReaderReadRecord(RecordIOHandle handle, char const** buf,
                               size_t* size);
int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos);
int MXRecordIOReaderTell(RecordIOHandle handle, size_t* pos);

/* CachedOp -------------------------------------------------------------- */
typedef void* CachedOpHandle;
int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle* out);
int MXCreateCachedOpEx(SymbolHandle sym, int num_flags, const char** keys,
                       const char** vals, CachedOpHandle* out);
int MXFreeCachedOp(CachedOpHandle handle);
int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle* inputs, int* num_outputs,
                     NDArrayHandle** outputs);
int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, const int** out_stypes);

/* Custom operators (C registration protocol) ---------------------------- */
/* Reference: include/mxnet/c_api.h:153-217 — struct-of-callbacks
   registration; the callee-owned MXCallbackList carries the prop/op/
   function callbacks plus their contexts. */
struct MXCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void** contexts;
};

enum CustomOpCallbacks {
  kCustomOpDelete,
  kCustomOpForward,
  kCustomOpBackward
};

enum CustomOpPropCallbacks {
  kCustomOpPropDelete,
  kCustomOpPropListArguments,
  kCustomOpPropListOutputs,
  kCustomOpPropListAuxiliaryStates,
  kCustomOpPropInferShape,
  kCustomOpPropDeclareBackwardDependency,
  kCustomOpPropCreateOperator,
  kCustomOpPropInferType,
  kCustomOpPropInferStorageType,
  kCustomOpPropBackwardInferStorageType
};

typedef int (*CustomOpFBFunc)(int size, void** ptrs, int* tags,
                              const int* reqs, const int is_train,
                              void* state);
typedef int (*CustomOpDelFunc)(void* state);
typedef int (*CustomOpListFunc)(char*** args, void* state);
typedef int (*CustomOpInferShapeFunc)(int num_input, int* ndims,
                                      int** shapes, void* state);
typedef int (*CustomOpInferTypeFunc)(int num_input, int* types, void* state);
typedef int (*CustomOpBwdDepFunc)(const int* out_grad, const int* in_data,
                                  const int* out_data, int* num_deps,
                                  int** rdeps, void* state);
typedef int (*CustomOpCreateFunc)(const char* ctx, int num_inputs,
                                  unsigned** shapes, const int* ndims,
                                  const int* dtypes,
                                  struct MXCallbackList* ret, void* state);
typedef int (*CustomOpPropCreator)(const char* op_type, const int num_kwargs,
                                   const char** keys, const char** values,
                                   struct MXCallbackList* ret);

enum CustomFunctionCallbacks {
  kCustomFunctionBackward,
  kCustomFunctionDelete
};

typedef int (*CustomFunctionBwdFunc)(int num_ograds, int num_igrads,
                                     void** ptrs, const int* reqs,
                                     const int is_train, void* state);
typedef int (*CustomFunctionDelFunc)(void* state);

int MXCustomOpRegister(const char* op_type, CustomOpPropCreator creator);
int MXCustomFunctionRecord(int num_inputs, NDArrayHandle* inputs,
                           int num_outputs, NDArrayHandle* outputs,
                           struct MXCallbackList* callbacks);

/* Test hooks (reference include/mxnet/c_api_test.h) --------------------- */
int MXBuildSubgraphByOpNames(SymbolHandle sym, const char* prop_name,
                             const uint32_t num_ops, const char** op_names,
                             SymbolHandle* ret);
int MXSetSubgraphPropertyOpNames(const char* prop_name,
                                 const uint32_t num_ops,
                                 const char** op_names);
int MXRemoveSubgraphPropertyOpNames(const char* prop_name);

/* Misc runtime ---------------------------------------------------------- */
int MXRandomSeed(int seed);
int MXEngineWaitAll(void);
int MXNotifyShutdown(void);
int MXSetNumOMPThreads(int n);
int MXStorageEmptyCache(int dev_type, int dev_id);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_API_H_ */
