// C++ binding over the MXNet-compatible C ABI — error handling + handle
// plumbing shared by all classes.
//
// Reference analog: cpp-package/include/mxnet-cpp/base.h.  Design differs:
// handles are PyObject-backed (the runtime is JAX), RAII is std::shared_ptr
// with the ABI's Free as deleter, errors become std::runtime_error carrying
// MXGetLastError().
#ifndef MXTPU_CPP_BASE_HPP_
#define MXTPU_CPP_BASE_HPP_

#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "../c_api.h"

namespace mxtpu {

inline void Check(int rc, const char* what) {
  if (rc != 0) {
    throw std::runtime_error(std::string(what) + ": " + MXGetLastError());
  }
}

// stringify op parameters the way the ABI expects (python literal syntax for
// tuples, lowercase bools)
inline std::string ParamStr(const std::string& v) { return v; }
inline std::string ParamStr(const char* v) { return v; }
inline std::string ParamStr(bool v) { return v ? "True" : "False"; }
template <typename T>
inline std::string ParamStr(const T& v) {
  std::ostringstream os;
  os << v;
  return os.str();
}
template <typename T>
inline std::string ParamStr(const std::vector<T>& v) {
  std::ostringstream os;
  os << "(";
  for (size_t i = 0; i < v.size(); ++i) os << (i ? ", " : "") << v[i];
  if (v.size() == 1) os << ",";
  os << ")";
  return os.str();
}

}  // namespace mxtpu

#endif  // MXTPU_CPP_BASE_HPP_
