// Symbol + Operator — symbolic graph construction from C++.
//
// Reference analog: cpp-package/include/mxnet-cpp/symbol.h + operator.h
// (Operator::SetParam/SetInput/CreateSymbol over MXSymbolCreateAtomicSymbol
// + MXSymbolCompose).  Here composition is the one-shot
// MXSymbolCreateFromOp; the graph itself lives in the runtime's Symbol IR
// (incubator_mxnet_tpu/symbol/symbol.py).
#ifndef MXTPU_CPP_SYMBOL_HPP_
#define MXTPU_CPP_SYMBOL_HPP_

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <utility>
#include <vector>

#include "base.hpp"

namespace mxtpu {

class Symbol {
 public:
  Symbol() = default;
  explicit Symbol(SymbolHandle h) : h_(h, MXSymbolFree) {}

  static Symbol Variable(const std::string& name) {
    SymbolHandle out = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &out),
          "MXSymbolCreateVariable");
    return Symbol(out);
  }

  static Symbol FromJSON(const std::string& json) {
    SymbolHandle out = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &out),
          "MXSymbolCreateFromJSON");
    return Symbol(out);
  }

  std::string ToJSON() const {
    const char* json = nullptr;
    Check(MXSymbolSaveToJSON(h_.get(), &json), "MXSymbolSaveToJSON");
    return json;
  }

  std::vector<std::string> ListArguments() const {
    return StrList(MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return StrList(MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return StrList(MXSymbolListAuxiliaryStates);
  }

  // Shape inference from known input shapes (MXSymbolInferShape CSR
  // marshalling).  Returns true when every shape is fully known.
  bool InferShape(
      const std::map<std::string, std::vector<uint32_t>>& known,
      std::vector<std::vector<uint32_t>>* arg_shapes,
      std::vector<std::vector<uint32_t>>* out_shapes,
      std::vector<std::vector<uint32_t>>* aux_shapes) const {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0};
    std::vector<uint32_t> data;
    for (const auto& kv : known) {
      keys.push_back(kv.first.c_str());
      data.insert(data.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(data.size()));
    }
    uint32_t sizes[3] = {0, 0, 0};
    const uint32_t* ndims[3] = {nullptr, nullptr, nullptr};
    const uint32_t** shapes[3] = {nullptr, nullptr, nullptr};
    int complete = 0;
    Check(MXSymbolInferShape(h_.get(),
                             static_cast<uint32_t>(keys.size()), keys.data(),
                             indptr.data(), data.data(), &sizes[0],
                             &ndims[0], &shapes[0], &sizes[1], &ndims[1],
                             &shapes[1], &sizes[2], &ndims[2], &shapes[2],
                             &complete),
          "MXSymbolInferShape");
    std::vector<std::vector<uint32_t>>* dsts[3] = {arg_shapes, out_shapes,
                                                   aux_shapes};
    for (int g = 0; g < 3; ++g) {
      if (dsts[g] == nullptr) continue;
      dsts[g]->clear();
      for (uint32_t i = 0; i < sizes[g]; ++i) {
        dsts[g]->emplace_back(shapes[g][i], shapes[g][i] + ndims[g][i]);
      }
    }
    return complete != 0;
  }

  SymbolHandle get() const { return h_.get(); }

 private:
  using ListFn = int (*)(SymbolHandle, uint32_t*, const char***);
  std::vector<std::string> StrList(ListFn fn) const {
    uint32_t n = 0;
    const char** arr = nullptr;
    Check(fn(h_.get(), &n, &arr), "MXSymbolList*");
    return std::vector<std::string>(arr, arr + n);
  }

  std::shared_ptr<void> h_;
};

// Fluent op-node builder (mxnet-cpp Operator semantics):
//   auto fc = Operator("FullyConnected").SetParam("num_hidden", 64)
//                 .SetInput("data", x).CreateSymbol("fc1");
class Operator {
 public:
  explicit Operator(const std::string& op_name) : op_(op_name) {}

  template <typename T>
  Operator& SetParam(const std::string& key, const T& value) {
    params_.emplace_back(key, ParamStr(value));
    return *this;
  }

  Operator& SetInput(const std::string& key, const Symbol& s) {
    inputs_.emplace_back(key, s);
    return *this;
  }

  Operator& AddInput(const Symbol& s) {
    inputs_.emplace_back("", s);
    return *this;
  }

  Symbol CreateSymbol(const std::string& name = "") {
    std::vector<const char*> pkeys, pvals, ikeys;
    for (const auto& kv : params_) {
      pkeys.push_back(kv.first.c_str());
      pvals.push_back(kv.second.c_str());
    }
    std::vector<SymbolHandle> ins;
    for (const auto& kv : inputs_) {
      ikeys.push_back(kv.first.empty() ? nullptr : kv.first.c_str());
      ins.push_back(kv.second.get());
    }
    SymbolHandle out = nullptr;
    Check(MXSymbolCreateFromOp(op_.c_str(),
                               static_cast<uint32_t>(pkeys.size()),
                               pkeys.data(), pvals.data(),
                               static_cast<uint32_t>(ins.size()),
                               ikeys.data(), ins.data(),
                               name.empty() ? nullptr : name.c_str(), &out),
          ("MXSymbolCreateFromOp(" + op_ + ")").c_str());
    return Symbol(out);
  }

 private:
  std::string op_;
  std::vector<std::pair<std::string, std::string>> params_;
  std::vector<std::pair<std::string, Symbol>> inputs_;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_SYMBOL_HPP_
