// NDArray — C++ tensor handle over the C ABI.
//
// Reference analog: cpp-package/include/mxnet-cpp/ndarray.h (NDArray class
// over MXNDArray*).  Own design: shared_ptr RAII, imperative ops through
// MXImperativeInvokeByName (optionally writing into caller buffers — the
// MXImperativeInvokeEx in-place contract).
#ifndef MXTPU_CPP_NDARRAY_HPP_
#define MXTPU_CPP_NDARRAY_HPP_

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <numeric>
#include <string>
#include <vector>

#include "base.hpp"

namespace mxtpu {

class NDArray {
 public:
  NDArray() = default;
  // Takes ownership of a handle returned by the ABI.
  explicit NDArray(NDArrayHandle h) : h_(h, MXNDArrayFree) {}

  explicit NDArray(const std::vector<uint32_t>& shape, int dtype = 0) {
    NDArrayHandle out = nullptr;
    Check(MXNDArrayCreateEx(shape.data(),
                            static_cast<uint32_t>(shape.size()), 1, 0, 0,
                            dtype, &out),
          "MXNDArrayCreateEx");
    h_ = std::shared_ptr<void>(out, MXNDArrayFree);
  }

  NDArray(const std::vector<uint32_t>& shape, const std::vector<float>& data)
      : NDArray(shape) {
    SyncCopyFromCPU(data.data(), data.size());
  }

  bool IsNull() const { return h_ == nullptr; }
  NDArrayHandle get() const { return h_.get(); }

  void SyncCopyFromCPU(const float* data, size_t size) {
    Check(MXNDArraySyncCopyFromCPU(h_.get(), data, size),
          "MXNDArraySyncCopyFromCPU");
  }

  void SyncCopyToCPU(float* data, size_t size) const {
    Check(MXNDArraySyncCopyToCPU(h_.get(), data, size),
          "MXNDArraySyncCopyToCPU");
  }

  std::vector<float> ToVector() const {
    std::vector<float> out(Size());
    SyncCopyToCPU(out.data(), out.size());
    return out;
  }

  std::vector<uint32_t> Shape() const {
    uint32_t ndim = 0;
    const uint32_t* data = nullptr;
    Check(MXNDArrayGetShape(h_.get(), &ndim, &data), "MXNDArrayGetShape");
    return std::vector<uint32_t>(data, data + ndim);
  }

  size_t Size() const {
    auto s = Shape();
    return std::accumulate(s.begin(), s.end(), size_t{1},
                           std::multiplies<size_t>());
  }

  int DType() const {
    int dt = 0;
    Check(MXNDArrayGetDType(h_.get(), &dt), "MXNDArrayGetDType");
    return dt;
  }

  void WaitToRead() const {
    Check(MXNDArrayWaitToRead(h_.get()), "MXNDArrayWaitToRead");
  }

  static void Save(const std::string& fname,
                   const std::map<std::string, NDArray>& arrays) {
    std::vector<NDArrayHandle> handles;
    std::vector<const char*> keys;
    for (const auto& kv : arrays) {
      keys.push_back(kv.first.c_str());
      handles.push_back(kv.second.get());
    }
    Check(MXNDArraySave(fname.c_str(),
                        static_cast<uint32_t>(handles.size()),
                        handles.data(), keys.data()),
          "MXNDArraySave");
  }

  static std::map<std::string, NDArray> Load(const std::string& fname) {
    uint32_t n = 0, nn = 0;
    NDArrayHandle* arrs = nullptr;
    const char** names = nullptr;
    Check(MXNDArrayLoad(fname.c_str(), &n, &arrs, &nn, &names),
          "MXNDArrayLoad");
    std::map<std::string, NDArray> out;
    for (uint32_t i = 0; i < n; ++i) {
      std::string key = nn == n ? names[i] : std::to_string(i);
      out.emplace(key, NDArray(arrs[i]));
    }
    return out;
  }

 private:
  std::shared_ptr<void> h_;
};

// Imperative invoke: run a registered op on NDArrays.  When `outs` is
// non-null its handles receive the results in place (optimizer updates);
// otherwise fresh arrays are returned.
inline std::vector<NDArray> Invoke(
    const std::string& op, const std::vector<NDArray>& inputs,
    const std::map<std::string, std::string>& params = {},
    std::vector<NDArray>* outs = nullptr) {
  std::vector<NDArrayHandle> ins;
  for (const auto& a : inputs) ins.push_back(a.get());
  std::vector<const char*> keys;
  std::vector<const char*> vals;
  for (const auto& kv : params) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  std::vector<NDArrayHandle> out_handles;
  int num_outputs = 0;
  NDArrayHandle* out_ptr = nullptr;
  if (outs != nullptr) {
    for (const auto& a : *outs) out_handles.push_back(a.get());
    num_outputs = static_cast<int>(out_handles.size());
    out_ptr = out_handles.data();
  }
  Check(MXImperativeInvokeByName(op.c_str(),
                                 static_cast<int>(ins.size()), ins.data(),
                                 &num_outputs, &out_ptr,
                                 static_cast<int>(keys.size()), keys.data(),
                                 vals.data()),
        ("MXImperativeInvokeByName(" + op + ")").c_str());
  if (outs != nullptr) return *outs;
  std::vector<NDArray> result;
  for (int i = 0; i < num_outputs; ++i) result.emplace_back(out_ptr[i]);
  return result;
}

inline NDArray operator+(const NDArray& a, const NDArray& b) {
  return Invoke("broadcast_add", {a, b})[0];
}
inline NDArray operator-(const NDArray& a, const NDArray& b) {
  return Invoke("broadcast_sub", {a, b})[0];
}
inline NDArray operator*(const NDArray& a, const NDArray& b) {
  return Invoke("broadcast_mul", {a, b})[0];
}
inline NDArray operator/(const NDArray& a, const NDArray& b) {
  return Invoke("broadcast_div", {a, b})[0];
}

}  // namespace mxtpu

#endif  // MXTPU_CPP_NDARRAY_HPP_
