// Executor — bound symbolic graph: forward / backward / outputs.
//
// Reference analog: cpp-package/include/mxnet-cpp/executor.h over
// MXExecutorBind/Forward/Backward/Outputs.  Gradient buffers passed at bind
// time are written in place by Backward (OpReqType kWriteTo/kAddTo), so the
// caller's handles always hold the latest gradients.
#ifndef MXTPU_CPP_EXECUTOR_HPP_
#define MXTPU_CPP_EXECUTOR_HPP_

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base.hpp"
#include "ndarray.hpp"
#include "symbol.hpp"

namespace mxtpu {

// OpReqType (include/mxnet/op_attr_types.h)
enum GradReq : uint32_t {
  kNullOp = 0,
  kWriteTo = 1,
  kWriteInplace = 2,
  kAddTo = 3,
};

class Executor {
 public:
  // in_args follow sym.ListArguments() order, aux_states follow
  // sym.ListAuxiliaryStates() order; arg_grads entries may be null
  // NDArrays (no gradient for that argument).
  Executor(const Symbol& sym, std::vector<NDArray> in_args,
           std::vector<NDArray> arg_grads, std::vector<uint32_t> grad_reqs,
           std::vector<NDArray> aux_states = {})
      : arg_arrays(std::move(in_args)),
        grad_arrays(std::move(arg_grads)),
        aux_arrays(std::move(aux_states)) {
    std::vector<NDArrayHandle> args, grads, aux;
    for (const auto& a : arg_arrays) args.push_back(a.get());
    for (const auto& g : grad_arrays) {
      grads.push_back(g.IsNull() ? nullptr : g.get());
    }
    for (const auto& a : aux_arrays) aux.push_back(a.get());
    ExecutorHandle out = nullptr;
    Check(MXExecutorBind(sym.get(), 1, 0,
                         static_cast<uint32_t>(args.size()), args.data(),
                         grads.empty() ? nullptr : grads.data(),
                         grad_reqs.empty() ? nullptr : grad_reqs.data(),
                         static_cast<uint32_t>(aux.size()), aux.data(),
                         &out),
          "MXExecutorBind");
    h_ = std::shared_ptr<void>(out, MXExecutorFree);
  }

  void Forward(bool is_train) {
    Check(MXExecutorForward(h_.get(), is_train ? 1 : 0), "MXExecutorForward");
    uint32_t n = 0;
    NDArrayHandle* outs = nullptr;
    Check(MXExecutorOutputs(h_.get(), &n, &outs), "MXExecutorOutputs");
    outputs.clear();
    for (uint32_t i = 0; i < n; ++i) outputs.emplace_back(outs[i]);
  }

  void Backward(const std::vector<NDArray>& head_grads = {}) {
    std::vector<NDArrayHandle> hg;
    for (const auto& g : head_grads) hg.push_back(g.get());
    Check(MXExecutorBackward(h_.get(),
                             static_cast<uint32_t>(hg.size()),
                             hg.empty() ? nullptr : hg.data()),
          "MXExecutorBackward");
  }

  std::vector<NDArray> arg_arrays;
  std::vector<NDArray> grad_arrays;
  std::vector<NDArray> aux_arrays;
  std::vector<NDArray> outputs;

 private:
  std::shared_ptr<void> h_;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_EXECUTOR_HPP_
