// Optimizer — parameter updates from C++ through the registered update ops.
//
// Reference analog: cpp-package/include/mxnet-cpp/optimizer.h (Optimizer
// registry dispatching to sgd_update/sgd_mom_update/adam_update...).  The
// update ops run as in-place imperative invokes (caller-provided outputs),
// so weights and optimizer state mutate exactly like the reference's
// kWriteInplace update kernels.
#ifndef MXTPU_CPP_OPTIMIZER_HPP_
#define MXTPU_CPP_OPTIMIZER_HPP_

#include <map>
#include <string>
#include <utility>
#include <vector>

#include "base.hpp"
#include "ndarray.hpp"

namespace mxtpu {

class Optimizer {
 public:
  // type: "sgd" (momentum optional) or "adam"
  explicit Optimizer(const std::string& type = "sgd", float lr = 0.01f,
                     float momentum = 0.0f, float wd = 0.0f)
      : type_(type), lr_(lr), momentum_(momentum), wd_(wd) {}

  void SetLearningRate(float lr) { lr_ = lr; }

  void Update(int index, NDArray& weight, const NDArray& grad) {
    std::map<std::string, std::string> p{{"lr", ParamStr(lr_)},
                                         {"wd", ParamStr(wd_)}};
    if (type_ == "adam") {
      auto& m = StateFor(index, weight, 0);
      auto& v = StateFor(index, weight, 1);
      std::vector<NDArray> outs{weight, m, v};
      Invoke("adam_update", {weight, grad, m, v}, p, &outs);
    } else if (momentum_ != 0.0f) {
      auto& m = StateFor(index, weight, 0);
      p["momentum"] = ParamStr(momentum_);
      std::vector<NDArray> outs{weight, m};
      Invoke("sgd_mom_update", {weight, grad, m}, p, &outs);
    } else {
      std::vector<NDArray> outs{weight};
      Invoke("sgd_update", {weight, grad}, p, &outs);
    }
  }

 private:
  NDArray& StateFor(int index, const NDArray& weight, int slot) {
    auto key = std::make_pair(index, slot);
    auto it = states_.find(key);
    if (it == states_.end()) {
      NDArray zeros = Invoke("zeros_like", {weight})[0];
      it = states_.emplace(key, zeros).first;
    }
    return it->second;
  }

  std::string type_;
  float lr_, momentum_, wd_;
  std::map<std::pair<int, int>, NDArray> states_;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_OPTIMIZER_HPP_
