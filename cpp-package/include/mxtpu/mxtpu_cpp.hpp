// Umbrella header for the C++ binding package (reference analog:
// cpp-package/include/mxnet-cpp/MxNetCpp.h).
#ifndef MXTPU_MXTPU_CPP_HPP_
#define MXTPU_MXTPU_CPP_HPP_

#include "c_api.h"
#include "cpp/base.hpp"
#include "cpp/ndarray.hpp"
#include "cpp/symbol.hpp"
#include "cpp/executor.hpp"
#include "cpp/optimizer.hpp"

#endif  // MXTPU_MXTPU_CPP_HPP_
