// C++ consumer of the MXNet-compatible C ABI (L9 binding path).
//
// Reference analog: cpp-package/ + example/image-classification/predict-cpp
// — a C++ program that loads a checkpoint (symbol JSON + params blob) and
// serves it through the C predict API (include/mxnet/c_predict_api.h:84,
// 254, 263, 289) with no Python in the source.  Linked against
// ../src/native/libmxtpu_capi.so.
//
// Build & run:  make run  (see Makefile; needs a model exported by
// make_model.py first).
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <string>
#include <vector>

typedef void* PredictorHandle;

extern "C" {
const char* MXGetLastError();
int MXGetVersion(int* out);
int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out);
int MXPredSetInput(PredictorHandle h, const char* key, const float* data,
                   uint32_t size);
int MXPredForward(PredictorHandle h);
int MXPredGetOutputShape(PredictorHandle h, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim);
int MXPredGetOutput(PredictorHandle h, uint32_t index, float* data,
                    uint32_t size);
int MXPredFree(PredictorHandle h);
}

static std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    std::fprintf(stderr, "cannot open %s\n", path.c_str());
    std::exit(2);
  }
  return std::string((std::istreambuf_iterator<char>(f)),
                     std::istreambuf_iterator<char>());
}

#define CHECK_RC(call)                                              \
  do {                                                              \
    if ((call) != 0) {                                              \
      std::fprintf(stderr, "FAIL %s: %s\n", #call, MXGetLastError()); \
      return 1;                                                     \
    }                                                               \
  } while (0)

int main(int argc, char** argv) {
  const std::string prefix = argc > 1 ? argv[1] : "model";
  int version = 0;
  CHECK_RC(MXGetVersion(&version));
  std::printf("libmxtpu_capi version %d\n", version);

  const std::string json = ReadFile(prefix + "-symbol.json");
  const std::string params = ReadFile(prefix + "-0000.params");

  const char* input_keys[] = {"data"};
  const uint32_t indptr[] = {0, 2};
  const uint32_t shape[] = {2, 8};
  PredictorHandle pred = nullptr;
  CHECK_RC(MXPredCreate(json.c_str(), params.data(),
                        static_cast<int>(params.size()), 1, 0, 1, input_keys,
                        indptr, shape, &pred));

  std::vector<float> x(2 * 8);
  for (size_t i = 0; i < x.size(); ++i) x[i] = 0.1f * static_cast<float>(i);
  CHECK_RC(MXPredSetInput(pred, "data", x.data(),
                          static_cast<uint32_t>(x.size())));
  CHECK_RC(MXPredForward(pred));

  uint32_t* oshape = nullptr;
  uint32_t ondim = 0;
  CHECK_RC(MXPredGetOutputShape(pred, 0, &oshape, &ondim));
  uint32_t total = 1;
  std::printf("output shape: (");
  for (uint32_t i = 0; i < ondim; ++i) {
    std::printf(i ? ", %u" : "%u", oshape[i]);
    total *= oshape[i];
  }
  std::printf(")\n");

  std::vector<float> out(total);
  CHECK_RC(MXPredGetOutput(pred, 0, out.data(), total));
  float sum = 0.0f;
  for (float v : out) sum += v;
  std::printf("output[0..3]: %.4f %.4f %.4f %.4f  (sum %.4f)\n", out[0],
              out[1], out[2], out[3], sum);
  CHECK_RC(MXPredFree(pred));
  std::printf("PREDICT_DEMO_OK\n");
  return 0;
}
