#!/usr/bin/env python
"""Export a small model checkpoint for the C++ predict demo."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu import symbol as sym
from incubator_mxnet_tpu.model import save_checkpoint


def main():
    prefix = sys.argv[1] if len(sys.argv) > 1 else "model"
    rng = np.random.RandomState(0)
    out = sym.FullyConnected(sym.var("data"), sym.var("w1"), sym.var("b1"),
                             num_hidden=16)
    out = sym.Activation(out, act_type="relu")
    out = sym.FullyConnected(out, sym.var("w2"), sym.var("b2"), num_hidden=4)
    out = sym.softmax(out)
    args = {"w1": nd.array(rng.normal(0, 0.5, (16, 8)).astype(np.float32)),
            "b1": nd.zeros((16,)),
            "w2": nd.array(rng.normal(0, 0.5, (4, 16)).astype(np.float32)),
            "b2": nd.zeros((4,))}
    save_checkpoint(prefix, 0, out, args, {})
    print("exported %s-symbol.json / %s-0000.params" % (prefix, prefix))


if __name__ == "__main__":
    main()
