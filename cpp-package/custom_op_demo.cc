// C custom operator registered through the struct-of-callbacks protocol
// (L7/L9 binding path) — the scenario the reference enables with
// MXCustomOpRegister (include/mxnet/c_api.h:3029, callback structs
// :153-206; dispatch src/operator/custom/custom.cc:70-119).
//
// Registers op "csquare" (y = x*x, dy/dx = 2*x*g) entirely in C — prop
// creator, list/infer callbacks, operator creation, forward/backward —
// then trains a tiny 1-parameter model through autograd so both
// directions execute.  No Python in this source; linked against
// ../src/native/libmxtpu_capi.so.
//
// Build & run:  make run-custom
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

typedef void* NDArrayHandle;

extern "C" {
struct MXCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void** contexts;
};

const char* MXGetLastError();
int MXCustomOpRegister(const char* op_type,
                       int (*creator)(const char*, const int, const char**,
                                      const char**, MXCallbackList*));
int MXNDArrayCreateEx(const uint32_t* shape, uint32_t ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle h);
int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void* data, size_t n);
int MXNDArraySyncCopyToCPU(NDArrayHandle h, void* data, size_t n);
int MXNDArrayGetShape(NDArrayHandle h, uint32_t* out_dim,
                      const uint32_t** out_pdata);
int MXImperativeInvokeByName(const char* op, int num_inputs,
                             NDArrayHandle* inputs, int* num_outputs,
                             NDArrayHandle** outputs, int num_params,
                             const char** keys, const char** vals);
int MXAutogradSetIsRecording(int is_recording, int* prev);
int MXAutogradMarkVariables(uint32_t num_var, NDArrayHandle* var_handles,
                            uint32_t* grad_reqs,
                            NDArrayHandle* grad_handles);
int MXAutogradBackward(uint32_t num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph);
}

namespace {

void Check(int rc, const char* what) {
  if (rc != 0) {
    std::fprintf(stderr, "FAIL %s: %s\n", what, MXGetLastError());
    std::exit(1);
  }
}

size_t NumElems(NDArrayHandle h) {
  uint32_t ndim = 0;
  const uint32_t* shape = nullptr;
  Check(MXNDArrayGetShape(h, &ndim, &shape), "GetShape");
  size_t n = 1;
  for (uint32_t i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

// ---- op callbacks (CustomOpCallbacks order: delete, forward, backward)

// Ownership of every handle transfers to the callback (the engine
// allocates per-call NDArrays, custom.cc ForwardEx/BackwardEx); free
// each one via MXNDArrayFree once done — the underlying buffers live on
// in the graph's own NDArrays.
void FreeAll(int size, void** ptrs) {
  for (int i = 0; i < size; ++i) {
    Check(MXNDArrayFree(ptrs[i]), "MXNDArrayFree(callback handle)");
  }
}

int Forward(int size, void** ptrs, int* tags, const int* /*reqs*/,
            const int /*is_train*/, void* /*state*/) {
  NDArrayHandle in = nullptr, out = nullptr;
  for (int i = 0; i < size; ++i) {
    if (tags[i] == 0) in = ptrs[i];
    if (tags[i] == 1) out = ptrs[i];
  }
  size_t n = NumElems(in);
  std::vector<float> x(n);
  Check(MXNDArraySyncCopyToCPU(in, x.data(), n), "fwd CopyToCPU");
  for (float& v : x) v = v * v;
  Check(MXNDArraySyncCopyFromCPU(out, x.data(), n), "fwd CopyFromCPU");
  FreeAll(size, ptrs);
  return 1;
}

int Backward(int size, void** ptrs, int* tags, const int* /*reqs*/,
             const int /*is_train*/, void* /*state*/) {
  // bwd tags: 3=out_grad, 0=in_data, 2=in_grad (custom.cc:373)
  NDArrayHandle og = nullptr, in = nullptr, ig = nullptr;
  for (int i = 0; i < size; ++i) {
    if (tags[i] == 3) og = ptrs[i];
    if (tags[i] == 0) in = ptrs[i];
    if (tags[i] == 2) ig = ptrs[i];
  }
  size_t n = NumElems(in);
  std::vector<float> x(n), g(n);
  Check(MXNDArraySyncCopyToCPU(in, x.data(), n), "bwd CopyToCPU x");
  Check(MXNDArraySyncCopyToCPU(og, g.data(), n), "bwd CopyToCPU g");
  for (size_t i = 0; i < n; ++i) g[i] = 2.0f * x[i] * g[i];
  Check(MXNDArraySyncCopyFromCPU(ig, g.data(), n), "bwd CopyFromCPU");
  FreeAll(size, ptrs);
  return 1;
}

typedef int (*RawFn)(void);

int CreateOperator(const char* /*ctx*/, int /*num_inputs*/,
                   unsigned** /*shapes*/, const int* /*ndims*/,
                   const int* /*dtypes*/, MXCallbackList* ret,
                   void* /*state*/) {
  static RawFn cbs[3] = {nullptr, reinterpret_cast<RawFn>(Forward),
                         reinterpret_cast<RawFn>(Backward)};
  static void* ctxs[3] = {nullptr, nullptr, nullptr};
  ret->num_callbacks = 3;
  ret->callbacks = cbs;
  ret->contexts = ctxs;
  return 1;
}

// ---- prop callbacks

int ListArgs(char*** out, void* /*state*/) {
  static const char* names[] = {"data", nullptr};
  *out = const_cast<char**>(names);
  return 1;
}

int ListOuts(char*** out, void* /*state*/) {
  static const char* names[] = {"output", nullptr};
  *out = const_cast<char**>(names);
  return 1;
}

int ListAux(char*** out, void* /*state*/) {
  static const char* names[] = {nullptr};
  *out = const_cast<char**>(names);
  return 1;
}

int InferShape(int /*num_input*/, int* ndims, int** shapes,
               void* /*state*/) {
  ndims[1] = ndims[0];  // output shape := input shape
  shapes[1] = shapes[0];
  return 1;
}

int BwdDep(const int* out_grad, const int* in_data, const int* /*out*/,
           int* num_deps, int** rdeps, void* /*state*/) {
  static int deps[2];
  deps[0] = out_grad[0];
  deps[1] = in_data[0];
  *num_deps = 2;
  *rdeps = deps;
  return 1;
}

int PropCreator(const char* /*op_type*/, const int /*num_kwargs*/,
                const char** /*keys*/, const char** /*vals*/,
                MXCallbackList* ret) {
  static RawFn cbs[8] = {nullptr,  // PropDelete
                         reinterpret_cast<RawFn>(ListArgs),
                         reinterpret_cast<RawFn>(ListOuts),
                         reinterpret_cast<RawFn>(ListAux),
                         reinterpret_cast<RawFn>(InferShape),
                         reinterpret_cast<RawFn>(BwdDep),
                         reinterpret_cast<RawFn>(CreateOperator),
                         nullptr};  // InferType (defaulted)
  static void* ctxs[8] = {nullptr};
  ret->num_callbacks = 8;
  ret->callbacks = cbs;
  ret->contexts = ctxs;
  return 1;
}

NDArrayHandle MakeND(const std::vector<float>& v) {
  NDArrayHandle h = nullptr;
  uint32_t shape[1] = {static_cast<uint32_t>(v.size())};
  Check(MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0, &h), "CreateEx");
  Check(MXNDArraySyncCopyFromCPU(h, v.data(), v.size()), "CopyFromCPU");
  return h;
}

}  // namespace

int main() {
  Check(MXCustomOpRegister("csquare", PropCreator), "MXCustomOpRegister");

  // forward+backward through autograd: y = csquare(x), dy/dx == 2x
  NDArrayHandle x = MakeND({1.0f, 2.0f, 3.0f, 4.0f});
  NDArrayHandle gx = MakeND({0.0f, 0.0f, 0.0f, 0.0f});
  uint32_t req[1] = {1};  // write
  NDArrayHandle vars[1] = {x};
  NDArrayHandle grads[1] = {gx};
  Check(MXAutogradMarkVariables(1, vars, req, grads), "MarkVariables");
  int prev = 0;
  Check(MXAutogradSetIsRecording(1, &prev), "SetIsRecording");

  int n_out = 0;
  NDArrayHandle* outs = nullptr;
  const char* keys[] = {"op_type"};
  const char* vals[] = {"csquare"};
  Check(MXImperativeInvokeByName("Custom", 1, vars, &n_out, &outs, 1, keys,
                                 vals),
        "Invoke Custom");
  if (n_out != 1) {
    std::fprintf(stderr, "expected 1 output, got %d\n", n_out);
    return 1;
  }
  Check(MXAutogradBackward(1, outs, nullptr, 0), "Backward");
  Check(MXAutogradSetIsRecording(0, &prev), "StopRecording");

  float y[4] = {0}, g[4] = {0};
  Check(MXNDArraySyncCopyToCPU(outs[0], y, 4), "read y");
  Check(MXNDArraySyncCopyToCPU(gx, g, 4), "read grad");
  const float want_y[4] = {1, 4, 9, 16};
  const float want_g[4] = {2, 4, 6, 8};
  for (int i = 0; i < 4; ++i) {
    if (std::fabs(y[i] - want_y[i]) > 1e-5f ||
        std::fabs(g[i] - want_g[i]) > 1e-5f) {
      std::fprintf(stderr, "MISMATCH at %d: y=%f g=%f\n", i, y[i], g[i]);
      return 1;
    }
  }
  std::printf("csquare C custom op: forward %g %g %g %g, grad %g %g %g %g\n",
              y[0], y[1], y[2], y[3], g[0], g[1], g[2], g[3]);
  std::printf("PASS\n");
  return 0;
}
