// End-to-end TRAINING in pure C++ through the binding package (L9).
//
// Reference analog: cpp-package/example/mlp.cpp — build an MLP symbolically,
// bind an Executor, run forward/backward, update weights with an Optimizer,
// watch the loss fall.  No Python in this source; the runtime is reached
// only through libmxtpu_capi.so.
//
// Task: binary classification of two Gaussian blobs in 8-D.  An MLP with
// one hidden layer separates them; training accuracy must reach >0.9 from
// a 0.5 start for the demo to pass.
#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include "include/mxtpu/mxtpu_cpp.hpp"

using mxtpu::Executor;
using mxtpu::NDArray;
using mxtpu::Operator;
using mxtpu::Optimizer;
using mxtpu::Symbol;

int main() {
  int version = 0;
  mxtpu::Check(MXGetVersion(&version), "MXGetVersion");
  std::printf("libmxtpu_capi version %d\n", version);

  const uint32_t kBatch = 64, kDim = 8, kHidden = 32, kClasses = 2;

  // ---- symbolic MLP: data -> fc1 -> relu -> fc2 -> SoftmaxOutput ----------
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  Symbol fc1 = Operator("FullyConnected")
                   .SetParam("num_hidden", kHidden)
                   .SetInput("data", data)
                   .CreateSymbol("fc1");
  Symbol act = Operator("Activation")
                   .SetParam("act_type", "relu")
                   .SetInput("data", fc1)
                   .CreateSymbol("relu1");
  Symbol fc2 = Operator("FullyConnected")
                   .SetParam("num_hidden", kClasses)
                   .SetInput("data", act)
                   .CreateSymbol("fc2");
  Symbol net = Operator("SoftmaxOutput")
                   .SetParam("normalization", "batch")  // mean over batch:
                   // keeps grads O(1) so SGD at lr 0.2 converges
                   .SetInput("data", fc2)
                   .SetInput("label", label)
                   .CreateSymbol("softmax");

  auto arg_names = net.ListArguments();
  std::printf("arguments:");
  for (const auto& n : arg_names) std::printf(" %s", n.c_str());
  std::printf("\n");

  // ---- infer shapes, allocate args + grads --------------------------------
  std::vector<std::vector<uint32_t>> arg_shapes, out_shapes;
  bool complete = net.InferShape({{"data", {kBatch, kDim}},
                                  {"softmax_label", {kBatch}}},
                                 &arg_shapes, &out_shapes, nullptr);
  if (!complete || out_shapes.empty()) {
    std::fprintf(stderr, "shape inference incomplete\n");
    return 1;
  }

  std::mt19937 rng(7);
  std::normal_distribution<float> gauss(0.0f, 0.1f);
  std::vector<NDArray> args, grads;
  std::vector<uint32_t> reqs;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    const bool is_input =
        arg_names[i] == "data" || arg_names[i] == "softmax_label";
    NDArray arr(arg_shapes[i]);
    if (!is_input) {  // xavier-ish init for parameters
      std::vector<float> w(arr.Size());
      for (auto& v : w) v = gauss(rng);
      arr.SyncCopyFromCPU(w.data(), w.size());
    }
    args.push_back(arr);
    grads.push_back(is_input ? NDArray() : NDArray(arg_shapes[i]));
    reqs.push_back(is_input ? mxtpu::kNullOp : mxtpu::kWriteTo);
  }

  Executor exe(net, args, grads, reqs);
  Optimizer opt("sgd", 0.2f, 0.9f, 1e-4f);

  // ---- synthetic two-blob dataset ----------------------------------------
  std::vector<float> x(kBatch * kDim), y(kBatch);
  auto make_batch = [&]() {
    for (uint32_t b = 0; b < kBatch; ++b) {
      float cls = static_cast<float>(b % 2);
      y[b] = cls;
      for (uint32_t d = 0; d < kDim; ++d) {
        x[b * kDim + d] = gauss(rng) * 5.0f + (cls ? 1.0f : -1.0f);
      }
    }
  };

  // ---- training loop ------------------------------------------------------
  float first_acc = -1.0f, acc = 0.0f;
  for (int epoch = 0; epoch < 30; ++epoch) {
    make_batch();
    // bound input handles are written in place; the executor sees the batch
    for (size_t i = 0; i < arg_names.size(); ++i) {
      if (arg_names[i] == "data") args[i].SyncCopyFromCPU(x.data(), x.size());
      if (arg_names[i] == "softmax_label") {
        args[i].SyncCopyFromCPU(y.data(), y.size());
      }
    }
    exe.Forward(true);
    exe.Backward();
    for (size_t i = 0; i < arg_names.size(); ++i) {
      if (!grads[i].IsNull()) {
        opt.Update(static_cast<int>(i), args[i], grads[i]);
      }
    }
    // accuracy on this batch from the softmax output
    auto probs = exe.outputs[0].ToVector();
    int correct = 0;
    for (uint32_t b = 0; b < kBatch; ++b) {
      int pred = probs[b * kClasses] > probs[b * kClasses + 1] ? 0 : 1;
      correct += pred == static_cast<int>(y[b]);
    }
    acc = static_cast<float>(correct) / kBatch;
    if (first_acc < 0.0f) first_acc = acc;
    if (epoch % 10 == 0 || epoch == 29) {
      std::printf("epoch %2d  batch accuracy %.3f\n", epoch, acc);
    }
  }

  // ---- save the trained parameters through the ABI ------------------------
  std::map<std::string, NDArray> params;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    if (!grads[i].IsNull()) params["arg:" + arg_names[i]] = args[i];
  }
  NDArray::Save("train_demo-0000.params", params);
  auto loaded = NDArray::Load("train_demo-0000.params");
  std::printf("saved+reloaded %zu params\n", loaded.size());

  if (acc < 0.9f) {
    std::fprintf(stderr, "FAIL: final accuracy %.3f < 0.9\n", acc);
    return 1;
  }
  std::printf("TRAIN_DEMO_OK (accuracy %.3f -> %.3f)\n", first_acc, acc);
  return 0;
}
