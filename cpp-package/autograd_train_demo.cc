// Imperative TRAINING in pure C++ through the autograd C ABI (no
// Symbol/Executor).
//
// Reference analog: the gluon/autograd flow driven from a binding —
// mark variables, record an imperative forward, MXAutogradBackward, and
// apply updates through a KVStore with a C updater callback
// (include/mxnet/c_api.h autograd + kvstore blocks).
//
// Task: logistic regression on two separable 8-D Gaussian blobs.  Loss
// must fall and accuracy reach >0.9 for the demo to pass.
#include <cmath>
#include <cstdio>
#include <cstring>
#include <random>
#include <vector>

#include "include/mxtpu/c_api.h"

namespace {

void Check(int rc, const char* what) {
  if (rc != 0) {
    std::fprintf(stderr, "%s failed: %s\n", what, MXGetLastError());
    std::exit(1);
  }
}

NDArrayHandle MakeND(const std::vector<float>& data,
                     const std::vector<uint32_t>& shape) {
  NDArrayHandle h = nullptr;
  Check(MXNDArrayCreateEx(shape.data(),
                          static_cast<uint32_t>(shape.size()), 1, 0, 0, 0,
                          &h),
        "MXNDArrayCreateEx");
  Check(MXNDArraySyncCopyFromCPU(h, data.data(), data.size()),
        "MXNDArraySyncCopyFromCPU");
  return h;
}

std::vector<float> ToVec(NDArrayHandle h, size_t n) {
  std::vector<float> out(n);
  Check(MXNDArraySyncCopyToCPU(h, out.data(), n), "MXNDArraySyncCopyToCPU");
  return out;
}

NDArrayHandle Invoke1(const char* op, std::vector<NDArrayHandle> ins,
                      std::vector<const char*> keys = {},
                      std::vector<const char*> vals = {}) {
  int n_out = 0;
  NDArrayHandle* outs = nullptr;
  Check(MXImperativeInvokeByName(
            op, static_cast<int>(ins.size()), ins.data(), &n_out, &outs,
            static_cast<int>(keys.size()), keys.data(), vals.data()),
        op);
  return outs[0];
}

// SGD through the kvstore updater: local -= lr * recv
void SgdUpdater(int key, NDArrayHandle recv, NDArrayHandle local,
                void* handle) {
  (void)key;
  (void)handle;
  NDArrayHandle scaled =
      Invoke1("_mul_scalar", {recv}, {"scalar"}, {"-0.2"});
  NDArrayHandle updated = Invoke1("elemwise_add", {local, scaled});
  // write back into the kvstore's local buffer via broadcast-free copy
  uint32_t ndim = 0;
  const uint32_t* shape = nullptr;
  Check(MXNDArrayGetShape(local, &ndim, &shape), "GetShape");
  size_t n = 1;
  for (uint32_t i = 0; i < ndim; ++i) n *= shape[i];
  std::vector<float> v(n);
  Check(MXNDArraySyncCopyToCPU(updated, v.data(), n), "CopyToCPU");
  Check(MXNDArraySyncCopyFromCPU(local, v.data(), n), "CopyFromCPU");
  // recv/local arrive owned (reference set_updater contract); the
  // kvstore keeps its own reference to local alive
  MXNDArrayFree(scaled);
  MXNDArrayFree(updated);
  MXNDArrayFree(recv);
  MXNDArrayFree(local);
}

}  // namespace

int main() {
  Check(MXRandomSeed(7), "MXRandomSeed");
  const uint32_t kBatch = 128, kDim = 8;

  // two Gaussian blobs around +-1.2/sqrt(D)
  std::mt19937 rng(0);
  std::normal_distribution<float> noise(0.f, 1.f);
  std::vector<float> xs(kBatch * kDim), ys(kBatch);
  for (uint32_t i = 0; i < kBatch; ++i) {
    const float sign = (i % 2 == 0) ? 1.f : -1.f;
    ys[i] = sign > 0 ? 1.f : 0.f;
    for (uint32_t d = 0; d < kDim; ++d) {
      xs[i * kDim + d] = sign * 1.2f / std::sqrt(float(kDim)) + noise(rng);
    }
  }
  NDArrayHandle x = MakeND(xs, {kBatch, kDim});
  NDArrayHandle y = MakeND(ys, {kBatch, 1});

  // parameters: w (D, 1), b (1,) — marked as autograd variables
  std::vector<float> w0(kDim);
  for (auto& v : w0) v = 0.01f * noise(rng);
  NDArrayHandle w = MakeND(w0, {kDim, 1});
  NDArrayHandle b = MakeND({0.f}, {1});
  NDArrayHandle gw = MakeND(std::vector<float>(kDim, 0.f), {kDim, 1});
  NDArrayHandle gb = MakeND({0.f}, {1});
  NDArrayHandle vars[2] = {w, b};
  uint32_t reqs[2] = {1, 1};
  NDArrayHandle grads[2] = {gw, gb};
  Check(MXAutogradMarkVariables(2, vars, reqs, grads),
        "MXAutogradMarkVariables");

  // kvstore applies the SGD update at push time
  KVStoreHandle kv = nullptr;
  Check(MXKVStoreCreate("local", &kv), "MXKVStoreCreate");
  Check(MXKVStoreSetUpdater(kv, SgdUpdater, nullptr), "SetUpdater");
  int keys[2] = {0, 1};
  Check(MXKVStoreInit(kv, 2, keys, vars), "MXKVStoreInit");

  float first_loss = 0.f, last_loss = 0.f;
  for (int epoch = 0; epoch < 40; ++epoch) {
    int prev = 0;
    Check(MXAutogradSetIsRecording(1, &prev), "SetIsRecording");
    // forward: sigmoid(x@w + b); loss = mean((p - y)^2)
    NDArrayHandle z = Invoke1("dot", {x, w});
    z = Invoke1("broadcast_add", {z, b});
    NDArrayHandle p = Invoke1("sigmoid", {z});
    NDArrayHandle d = Invoke1("elemwise_sub", {p, y});
    NDArrayHandle sq = Invoke1("square", {d});
    NDArrayHandle loss = Invoke1("mean", {sq});
    Check(MXAutogradSetIsRecording(0, &prev), "SetIsRecording(off)");
    Check(MXAutogradBackward(1, &loss, nullptr, 0), "MXAutogradBackward");

    // push gradients; updater applies w -= lr*g in place
    NDArrayHandle gs[2];
    Check(MXNDArrayGetGrad(w, &gs[0]), "GetGrad(w)");
    Check(MXNDArrayGetGrad(b, &gs[1]), "GetGrad(b)");
    Check(MXKVStorePush(kv, 2, keys, gs, 0), "MXKVStorePush");
    // pull the updated values back into the training parameters (the
    // standard push-grad / pull-weight cycle, kvstore.h usage)
    Check(MXKVStorePull(kv, 2, keys, vars, 0), "MXKVStorePull");

    last_loss = ToVec(loss, 1)[0];
    if (epoch == 0) first_loss = last_loss;
    if (epoch % 10 == 0) {
      std::printf("epoch %2d  loss %.4f\n", epoch, last_loss);
    }
  }

  // accuracy
  NDArrayHandle z = Invoke1("dot", {x, w});
  z = Invoke1("broadcast_add", {z, b});
  std::vector<float> p = ToVec(Invoke1("sigmoid", {z}), kBatch);
  int correct = 0;
  for (uint32_t i = 0; i < kBatch; ++i) {
    correct += ((p[i] > 0.5f) == (ys[i] > 0.5f)) ? 1 : 0;
  }
  const float acc = float(correct) / kBatch;
  std::printf("final loss %.4f (from %.4f), accuracy %.3f\n", last_loss,
              first_loss, acc);
  Check(MXKVStoreFree(kv), "MXKVStoreFree");
  Check(MXEngineWaitAll(), "MXEngineWaitAll");
  if (!(last_loss < first_loss && acc > 0.9f)) {
    std::fprintf(stderr, "FAIL: training did not converge\n");
    return 1;
  }
  std::printf("PASS\n");
  return 0;
}
